"""Shared CLI wiring for the resilience flags (mirrors
``observability.cli``).

All three example entry points expose the same resilience surface;
this module is its single implementation:

    add_resilience_args(parser)     # --checkpoint-steps /
                                    # --checkpoint-secs /
                                    # --preemption-grace / --resume-step
    handler = install_preemption(args)          # SIGTERM/SIGINT + env
    step_mgr = make_step_manager(args)
    ckpt = make_step_checkpointer(args, step_mgr, bundle_fn,
                                  preemption=handler, sink=sink,
                                  start_step=0)
    resumed = resume(args, epoch_mgr, step_mgr, like, sink=sink,
                     elastic=ElasticResume(mesh, dkfac, params))

``resume`` unifies the two checkpoint trees: epoch-indexed checkpoints
(the pre-r8 format, still written at ``--checkpoint-freq``) and
global-step-indexed ones under ``<checkpoint-dir>/steps/``. Both bundle
kinds carry the resume point in their scalars (``epoch`` = the epoch to
(re)enter, offset by ``step_in_epoch`` batches — see
``resilience.dataiter``); the newest point wins, so a stale step
checkpoint left behind by an old preemption can never resume training
backwards past a newer epoch checkpoint.
"""

from __future__ import annotations

import os
import traceback

from distributed_kfac_pytorch_tpu.resilience import faults as faults_lib
from distributed_kfac_pytorch_tpu.resilience import (
    policy as policy_lib,
    preemption as preemption_lib,
)
from distributed_kfac_pytorch_tpu.training import checkpoint as ckpt_lib

STEP_SUBDIR = 'steps'


def add_resilience_args(p) -> None:
    """Resilience flags (r8; see README "Fault tolerance")."""
    p.add_argument('--checkpoint-steps', type=int, default=0,
                   metavar='N',
                   help='save a global-step-indexed checkpoint every N '
                        'optimizer steps into <checkpoint-dir>/steps '
                        '(0 = epoch checkpoints only) — bounds '
                        'preemption loss for long epochs')
    p.add_argument('--checkpoint-secs', type=float, default=0.0,
                   metavar='S',
                   help='also step-checkpoint when S wall-clock seconds '
                        'have passed since the last one (0 = off; on a '
                        "pod, rank 0's clock decides and the verdict "
                        'is broadcast so the collective save stays in '
                        'lockstep)')
    p.add_argument('--preemption-grace', type=float, default=30.0,
                   metavar='S',
                   help='grace budget after SIGTERM/SIGINT (or a '
                        'KFAC_PREEMPT_FILE sentinel): finish the '
                        'in-flight step, force a blocking step '
                        'checkpoint, exit with code '
                        f'{preemption_lib.RELAUNCH_EXIT_CODE} so a '
                        'relaunch loop restarts the run (a second '
                        'signal kills immediately)')
    p.add_argument('--resume-step', type=int, default=None, metavar='G',
                   help='resume from this exact global-step checkpoint '
                        'in <checkpoint-dir>/steps (default: the '
                        'newest of step/epoch checkpoints)')


def install_preemption(args) -> preemption_lib.PreemptionHandler:
    """Install the signal handler (plus the ``KFAC_PREEMPT_FILE``
    sentinel source when set). Call EARLY in main() — a preemption
    notice arriving before installation kills the process with the
    default disposition."""
    handler = preemption_lib.PreemptionHandler(
        grace_secs=args.preemption_grace).install()
    sentinel = os.environ.get('KFAC_PREEMPT_FILE')
    if sentinel:
        handler.add_source(preemption_lib.file_source(sentinel))
    return handler


def make_step_manager(args) -> ckpt_lib.CheckpointManager:
    """The global-step-indexed manager under ``<checkpoint-dir>/steps``
    (orbax ignores the non-integer subdirectory when scanning the
    parent epoch tree)."""
    return ckpt_lib.CheckpointManager(
        os.path.join(args.checkpoint_dir, STEP_SUBDIR), max_to_keep=2)


def make_step_checkpointer(args, step_mgr, bundle_fn, *,
                           preemption=None, sink=None,
                           start_step: int = 0
                           ) -> policy_lib.StepCheckpointer:
    """Assemble the per-step hook: interval policy + preemption forcing
    + any ``KFAC_CHAOS`` fault plan. Always constructed (even with both
    intervals at 0) because preemption must be able to force a save."""
    pol = policy_lib.CheckpointPolicy(
        every_steps=args.checkpoint_steps,
        every_secs=args.checkpoint_secs, start_step=start_step)
    return policy_lib.StepCheckpointer(
        step_mgr, pol, bundle_fn, preemption=preemption, sink=sink,
        plan=faults_lib.plan_from_env())


def resume(args, epoch_mgr, step_mgr, like, *, sink=None,
           verbose: bool = False, elastic=None):
    """Restore the newest checkpoint (step or epoch tree), if any.

    Returns ``(restored_tree, start_epoch, start_offset, source)`` or
    None when there is nothing to resume (or ``--no-resume``).
    ``like`` must be a live-state bundle template: restore always goes
    through ``like=`` so sharded SPMD state comes back with its
    committed shardings (restore without ``like`` yields host arrays —
    see ``CheckpointManager.restore``).

    ``elastic``: an ``elastic.ElasticResume(mesh=, dkfac=, params=)``
    describing the LIVE world. With it, a bundle saved on a DIFFERENT
    topology (detected from its recorded ``topo_*`` scalars,
    ``elastic.topology``) is restored replicated onto the live mesh
    (``CheckpointManager.restore_replicated``) and its K-FAC slot
    stacks are repacked for the new KAISA grid
    (``elastic.reshard``) instead of the restore failing — the
    grow/shrink resume path (README "Elastic training"). A
    ``topology_change`` event is emitted into ``sink``. Bundles that
    predate the topology record restore same-topology-only (their
    inverse stacks are rebuilt from factors if the layout happens to
    differ — ``DistributedKFAC.load_state_dict``'s shape check).
    Without ``elastic``, behavior is unchanged (same-topology
    ``like=`` restores).
    """
    if getattr(args, 'no_resume', False):
        return None
    # Known tradeoff: picking the winner needs the step bundle's
    # scalars, and orbax StandardRestore is whole-tree, so a stale step
    # checkpoint costs one discarded full restore before the epoch one
    # loads. That only happens on the first relaunch after an old
    # preemption was overtaken by epoch checkpoints — accepted over
    # maintaining a second scalars-only manifest.
    candidates = []  # ((epoch, offset), tree, source, label, relaid, mgr)
    step_label = (args.resume_step if args.resume_step is not None
                  else step_mgr.latest_epoch())
    if args.resume_step is not None or step_label is not None:
        tree, relaid = _restore(step_mgr, step_label, like, args,
                                what=f'step checkpoint {step_label}',
                                elastic=elastic)
        sc = tree['scalars']
        candidates.append(((int(sc['epoch']), int(sc['step_in_epoch'])),
                           tree, 'step', step_label, relaid, step_mgr))
    if args.resume_step is None:
        e = epoch_mgr.latest_epoch()
        if e is not None:
            # Epoch bundles record their resume point too ((e+1, 0) —
            # the epoch completed); restore only if it could win.
            if not candidates or (e + 1, 0) > candidates[0][0]:
                tree, relaid = _restore(epoch_mgr, e, like, args,
                                        what=f'epoch checkpoint {e}',
                                        elastic=elastic)
                sc = tree['scalars']
                candidates.append(
                    ((int(sc['epoch']), int(sc['step_in_epoch'])),
                     tree, 'epoch', e, relaid, epoch_mgr))
    if not candidates:
        return None
    (start_epoch, offset), tree, source, label, relaid, won_mgr = max(
        candidates, key=lambda c: c[0])
    if elastic is not None:
        tree = _adopt_topology(tree, elastic, relaid, won_mgr, label,
                               like, sink=sink, verbose=verbose)
    # The bundle's data_seed is part of the data-stream position
    # (resilience.dataiter): adopt it, or a supervisor that relaunches
    # without --seed would skip `offset` batches of a DIFFERENT
    # permutation — silently double-training some samples and never
    # seeing others.
    saved_seed = tree['scalars'].get('data_seed')
    if saved_seed is not None and hasattr(args, 'seed'):
        saved_seed = int(saved_seed)
        if saved_seed != args.seed:
            if verbose:
                print(f'resume: adopting checkpoint data_seed '
                      f'{saved_seed} (relaunch passed --seed '
                      f'{args.seed}) to keep the batch replay exact')
            args.seed = saved_seed
    if sink is not None:
        sink.event_record('restore', source=source, label=int(label),
                          global_step=int(tree['scalars']['step']),
                          epoch=start_epoch, step_in_epoch=offset)
    if verbose:
        at = f', mid-epoch offset {offset}' if offset else ''
        print(f'resumed from {source} checkpoint {label} '
              f'(epoch {start_epoch}{at})')
    return tree, start_epoch, offset, source


def _restore(mgr, label, like, args, *, what: str, elastic=None):
    """Restore one candidate bundle.

    Returns ``(tree, relaid)``; ``relaid`` is True when the bundle came
    back through the replicated (topology-independent) restore path
    and so needs re-committing onto the live mesh shardings.
    """
    try:
        if elastic is None:
            return mgr.restore(label, like=like), False
        return _elastic_restore(mgr, label, like, elastic)
    except FileNotFoundError as e:
        # Already self-explanatory (names the requested step and the
        # steps on disk) — don't bury it under the format advice.
        raise SystemExit(f'cannot resume from {what}: {e}')
    except Exception as e:
        traceback.print_exc()  # keep the real cause diagnosable
        raise SystemExit(
            f'cannot resume from {what} under {args.checkpoint_dir}: '
            f'{e}\nThe checkpoint was likely written with a different '
            'model/K-FAC configuration, or by a version predating the '
            'resilience checkpoint-format extension (see MIGRATION.md '
            '"Checkpoint format") — pass --no-resume or a fresh '
            '--checkpoint-dir.')


def _elastic_restore(mgr, label, like, elastic):
    """Same-topology fast path when the saved shapes match the live
    template; otherwise the replicated cross-topology restore."""
    from distributed_kfac_pytorch_tpu.elastic import (
        reshard as reshard_lib,
    )
    md = None
    try:
        md = mgr.metadata_tree(label)
    except Exception:
        md = None  # metadata unreadable: same-topology restore only
    if md is None or reshard_lib.like_matches_metadata(md, like):
        try:
            return mgr.restore(label, like=like), False
        except Exception:
            if md is None:
                raise
            # The positional shape match was a coincidence (structure
            # differed) — the replicated restore below is authoritative.
    return mgr.restore_replicated(label, mesh=elastic.mesh,
                                  like=like), True


def _adopt_topology(tree, elastic, relaid, mgr, label, like, *,
                    sink=None, verbose=False):
    """Post-restore elastic step: reshard the winner's K-FAC state for
    the live world when its recorded topology differs, and re-commit
    replicated-restored groups onto the live mesh."""
    from distributed_kfac_pytorch_tpu.elastic import (
        topology as topo_lib,
    )
    saved = topo_lib.TopologySpec.from_scalars(tree.get('scalars', {}))
    live = elastic.topology
    if saved is not None and saved.needs_reshard(live):
        if not relaid:
            # Same shapes, different slot layout (possible when two
            # KAISA grids coincide in slot counts): the like= restore
            # handed back row-sharded arrays, which cannot be gathered
            # host-side on a pod — re-restore replicated.
            tree = mgr.restore_replicated(label, mesh=elastic.mesh,
                                          like=like)
        tree = elastic.reshard_tree(tree, saved)
    elif relaid:
        # Same layout (or a pre-topology bundle) through the replicated
        # path: no reshard, but the groups still need committing onto
        # the live mesh.
        tree = elastic.reshard_tree(tree, None)
    if saved is not None and saved != live:
        if sink is not None:
            sink.event_record(
                'topology_change',
                global_step=int(tree['scalars']['step']),
                resharded=bool(saved.needs_reshard(live)),
                from_processes=saved.processes, to_processes=live.processes,
                from_devices=saved.devices, to_devices=live.devices,
                from_grid=f'{saved.rows}x{saved.cols}',
                to_grid=f'{live.rows}x{live.cols}')
        if verbose:
            print(f'elastic resume: topology changed — saved on '
                  f'{saved.describe()}, resuming on {live.describe()}'
                  + ('' if saved.needs_reshard(live)
                     else ' (layout-compatible, no reshard)'))
    return tree
