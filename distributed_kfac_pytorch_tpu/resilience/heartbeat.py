"""Per-rank heartbeat leases: the liveness signal the supervisor watches.

A training process that *crashes* reports itself (nonzero exit). A
process that *hangs* — a wedged collective, a deadlocked host thread, an
I/O stall — reports nothing, which is exactly why hangs are the fault
class that historically needed a human: the job looks alive to the
scheduler forever. Heartbeat leases close that gap (ISSUE r17):

  - Every rank writes a small JSON **lease file**
    (``<dir>/rank<r>.lease``) from the train loop at a configurable
    step stride, carrying its global step, wall time, pid and launch
    incarnation. Writes use the sink's atomicity discipline (write to
    ``<path>.tmp.<pid>``, fsync, ``os.replace``) so a reader never
    observes a torn lease — a lease either exists whole or not at all.
  - The **supervisor** (:mod:`supervisor`) scans the lease directory:
    a lease that stops advancing past ``--hang-timeout`` is a hang
    (kill and relaunch); a *subset* of ranks going stale past the
    failover grace while others stay fresh is a dead worker (shrink to
    the survivor mesh via the r11 elastic resume).

Heartbeats are pure host-side file I/O on the already-host-bound step
loop — no device interaction, no effect on the compiled program, so
heartbeats-off is trivially bit-identical and heartbeats-on adds zero
retraces (both pinned by tests/test_supervisor.py).

Clock discipline: lease freshness is judged by comparing the lease's
``wall_time`` (writer's clock) against the reader's clock. On shared
filesystems the two can skew; :func:`lease_age` clamps a
future-stamped lease to age 0 (fresh) — a skewed-but-beating worker
must never read as hung, while a genuinely stale lease only looks
*fresher* by the skew, which the timeout budgets absorb (set
``--hang-timeout`` comfortably above the worst step+eval gap plus
clock skew).
"""

from __future__ import annotations

import json
import os
import time

#: Env var naming the lease directory; the supervisor sets it for its
#: child so the training CLIs heartbeat without command-line rewriting
#: (``resilience.cli.make_heartbeat`` reads it as the default for
#: ``--heartbeat-dir``).
ENV_DIR = 'KFAC_HEARTBEAT_DIR'
#: Env var carrying the supervisor's launch counter; stamped into each
#: lease so the watcher (and post-mortems) can tell which incarnation
#: a lease belongs to.
ENV_INCARNATION = 'KFAC_INCARNATION'

LEASE_SCHEMA = 1


def lease_path(directory: str, rank: int) -> str:
    """``<dir>/rank<r>.lease`` — one lease per process, overwritten in
    place (atomically) on every beat."""
    return os.path.join(directory, f'rank{int(rank)}.lease')


def write_lease(path: str, *, rank: int, step: int, incarnation: int = 0,
                clock=time.time) -> dict:
    """Atomically publish one lease (write-tmp, fsync, rename — the
    sink's discipline, so no reader ever sees a torn lease). Returns
    the record written."""
    rec = {
        'schema': LEASE_SCHEMA,
        'rank': int(rank),
        'pid': os.getpid(),
        'step': int(step),
        'wall_time': float(clock()),
        'incarnation': int(incarnation),
    }
    tmp = f'{path}.tmp.{os.getpid()}'
    with open(tmp, 'w') as f:
        json.dump(rec, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return rec


def read_lease(path: str) -> dict | None:
    """One lease, or None when absent. Raises ``ValueError`` on an
    undecodable/ill-formed file — with atomic publication that means
    real corruption (or a foreign file), not a caught-mid-write race,
    so it is worth surfacing rather than treating as missing."""
    try:
        with open(path) as f:
            rec = json.load(f)
    except FileNotFoundError:
        return None
    except json.JSONDecodeError as e:
        raise ValueError(f'{path}: undecodable lease: {e}') from e
    if not isinstance(rec, dict) or not isinstance(
            rec.get('wall_time'), (int, float)):
        raise ValueError(f'{path}: not a lease record: {rec!r}')
    return rec


def lease_age(lease: dict, now: float | None = None) -> float:
    """Seconds since the lease was written, clamped at 0.

    The clamp is the clock-skew tolerance: a lease stamped (slightly)
    in the future by a skewed writer clock reads as *fresh*, never as
    a negative age an arithmetic comparison could misorder. Pinned by
    tests/test_supervisor.py.
    """
    if now is None:
        now = time.time()
    return max(0.0, now - float(lease['wall_time']))


def scan_leases(directory: str, incarnation: int | None = None
                ) -> tuple[dict[int, dict], dict[str, str]]:
    """All readable leases in ``directory`` plus per-file errors.

    Returns ``({rank: lease}, {filename: error})`` — an unreadable
    lease degrades to an error entry instead of failing the scan (one
    sick rank must not blind the watcher to the rest of the mesh).

    ``incarnation``: when given, only leases stamped with that launch
    incarnation count as live; a mixed-incarnation lease — left behind
    by an earlier launch, or by a quarantined job that shared the
    directory — degrades to an error entry instead of masquerading as
    a live rank (its stale timestamp would otherwise fire an instant
    false hang/dead-rank verdict; r18 satellite).
    """
    leases: dict[int, dict] = {}
    errors: dict[str, str] = {}
    try:
        names = sorted(os.listdir(directory))
    except FileNotFoundError:
        return {}, {}
    for name in names:
        if not (name.startswith('rank') and name.endswith('.lease')):
            continue
        try:
            rank = int(name[len('rank'):-len('.lease')])
        except ValueError:
            continue
        try:
            lease = read_lease(os.path.join(directory, name))
        except ValueError as e:
            errors[name] = str(e)
            continue
        if lease is None:
            continue
        if incarnation is not None:
            try:
                inc = int(lease.get('incarnation', 0))
            except (TypeError, ValueError):
                # A corrupt/foreign incarnation field degrades like
                # any other unreadable lease — one sick rank must not
                # crash the watcher.
                errors[name] = (f'bad incarnation field '
                                f'{lease.get("incarnation")!r}')
                continue
            if inc != int(incarnation):
                errors[name] = (f'stale incarnation {inc} '
                                f'(watching incarnation '
                                f'{incarnation})')
                continue
        leases[rank] = lease
    return leases, errors


def clear_leases(directory: str) -> None:
    """Remove every lease (and stray lease tmp) in ``directory``.

    The supervisor calls this before each launch: leases from the
    previous incarnation are that incarnation's last words — once read
    for failure classification they must not linger, or a relaunch on
    a smaller world would immediately re-trigger the dead-rank
    detector on the old world's orphaned lease files.
    """
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return
    for name in names:
        if name.startswith('rank') and ('.lease' in name):
            try:
                os.unlink(os.path.join(directory, name))
            except FileNotFoundError:
                pass


class HeartbeatEmitter:
    """Step-loop lease writer for one rank (``train_epoch(heartbeat=)``).

    ``beat(step)`` is called once per completed optimizer step; a lease
    is published when ``step % every == 0`` (stride keyed to the
    *global* step, so a resumed run keeps the same cadence) and always
    on the first call after construction (a resume at an off-stride
    step must not stay invisible for up to ``every`` steps).
    ``close()`` publishes a final lease so the last completed step is
    on disk even when the stride would have skipped it — that step
    number is what the supervisor's crash-loop detector keys on.
    """

    def __init__(self, directory: str, rank: int, *, every: int = 1,
                 incarnation: int | None = None, clock=time.time):
        if every < 1:
            raise ValueError(f'heartbeat stride must be >= 1, got {every}')
        self.directory = directory
        self.rank = int(rank)
        self.every = int(every)
        if incarnation is None:
            incarnation = int(os.environ.get(ENV_INCARNATION, '0') or 0)
        self.incarnation = int(incarnation)
        self._clock = clock
        self._last_step: int | None = None
        self._beaten = False
        os.makedirs(directory, exist_ok=True)

    @property
    def path(self) -> str:
        return lease_path(self.directory, self.rank)

    def beat(self, step: int) -> None:
        """Record one completed step (published every ``every`` steps)."""
        step = int(step)
        self._last_step = step
        if self._beaten and step % self.every:
            return
        self._beaten = True
        write_lease(self.path, rank=self.rank, step=step,
                    incarnation=self.incarnation, clock=self._clock)

    def close(self) -> None:
        """Publish the final lease (off-stride last step included)."""
        if self._last_step is not None:
            write_lease(self.path, rank=self.rank, step=self._last_step,
                        incarnation=self.incarnation, clock=self._clock)
