"""The K-FAC distributed gradient preconditioner (TPU-native core).

Functional redesign of the reference orchestrator
(kfac/preconditioner.py:39-735). The reference is a torch Optimizer that
mutates per-layer state through hooks; here the preconditioner is a pure
state transition

    precond_grads, new_state = kfac.step(state, grads, captures, ...)

with all per-layer state (running-average factors, eigendecompositions,
step counter) carried in one pytree. The whole pipeline — factor EWMA,
inverse/eigendecomposition, preconditioning, KL clipping — traces into a
single XLA program:

  - periodic work (``factor_update_freq`` / ``inv_update_freq`` gating,
    reference preconditioner.py:494-510) is ``lax.cond`` on the on-device
    step counter, so cadences are runtime-schedulable without recompiles;
  - the O(n^3) eigendecompositions are *bucketed by factor size* and run as
    one vmapped ``eigh`` per bucket — large batched MXU-friendly kernels
    instead of ~100 tiny sequential ones (and the natural unit for
    sharding inverse work across the mesh);
  - the KL-clip scale (reference preconditioner.py:661-682) is an on-device
    scalar — no per-layer ``.item()`` device->host syncs.

Distribution: factor *statistics* need no explicit collectives — captures
are batch-sharded over the mesh and XLA turns the covariance contraction
into a psum (the allreduce of reference preconditioner.py:525-533).
COMM_OPT / MEM_OPT / HYBRID_OPT placement of inverse and preconditioning
work lives in ``parallel.distributed``.
"""

from __future__ import annotations

import enum
import warnings
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_kfac_pytorch_tpu import fp16 as fp16_ops
from distributed_kfac_pytorch_tpu import layers as L
from distributed_kfac_pytorch_tpu.observability import (
    metrics as obs_metrics,
)
from distributed_kfac_pytorch_tpu.observability import profiling
from distributed_kfac_pytorch_tpu.capture import (CONV2D, CONV2D_GROUPED,
                                                  EMBEDDING, KFAC_REDUCE,
                                                  LINEAR, KFACCapture,
                                                  subsample_captures)
from distributed_kfac_pytorch_tpu.ops import factors as F
from distributed_kfac_pytorch_tpu.ops import linalg
from distributed_kfac_pytorch_tpu.ops import pallas_kernels


class CommMethod(enum.Enum):
    """Communication strategy (reference preconditioner.py:19-36).

    - COMM_OPT: every device holds all inverses and preconditions its own
      gradients; inverses are all-gathered after computation ('KFAC_opt').
    - MEM_OPT: each layer's inverses live on one device, which computes the
      preconditioned gradient and broadcasts it ('KFAC_lw').
    - HYBRID_OPT: a ``grad_worker_fraction`` of devices per layer hold
      inverses and precondition; the rest receive the result (KAISA).
    """
    COMM_OPT = 1
    MEM_OPT = 2
    HYBRID_OPT = 3


def cadence_gate(flag: bool | None, step, freq, do, keep):
    """Shared static/dynamic gating for periodic pipeline stages.

    ``flag=None`` gates dynamically — ``lax.cond(step % freq == 0)`` on
    the on-device counter; a Python bool is static — the stage is simply
    present or absent from the trace (the TPU fast path, see
    :meth:`KFAC.step`). Single point of truth so the single-chip and
    SPMD pipelines cannot drift.
    """
    if flag is None:
        return jax.lax.cond(step % freq == 0, do, keep)
    return do() if flag else keep()


def _tree_size_bytes(tree) -> int:
    return sum(x.size * x.dtype.itemsize
               for x in jax.tree.leaves(tree) if hasattr(x, 'size'))


def _fused_bucket_ok(entry: dict) -> bool:
    """Static eligibility of one stacked inverse bucket for the r21
    fused precondition kernel: full-rank eigen (square QA/QG — the r19
    truncated low-rank bases are rectangular and keep the stock
    dispatch) or baked A_inv/G_inv, with both factor dims inside the
    Pallas budget. Shared by the single-chip bucketing and the KAISA
    row-sharded path so the eligibility rule cannot drift."""
    if 'QA' in entry:
        qa, qg = entry['QA'], entry['QG']
        if (qa.shape[-1] != qa.shape[-2]
                or qg.shape[-1] != qg.shape[-2]):
            return False
        dims = (qa.shape[-1], qg.shape[-1])
    else:
        dims = (entry['A_inv'].shape[-1], entry['G_inv'].shape[-1])
    return all(1 <= d <= pallas_kernels.MAX_PALLAS_DIM for d in dims)


class KFAC:
    """K-FAC gradient preconditioner over a flax model.

    Hyperparameter surface mirrors the reference constructor
    (kfac/preconditioner.py:135-214); torch-specific knobs (grad_scaler —
    bf16 needs no loss scaling; compute_factor_in_hook — capture is fused
    into the step by construction) are intentionally absent.

    Args:
      model: flax module to precondition (registration walks its Dense /
        Conv / Embed submodules, minus ``skip_layers``).
      damping: Tikhonov damping (default 0.001).
      factor_decay: running-average coefficient for factors (default 0.95).
      factor_update_freq: steps between factor statistic updates (def. 10).
      inv_update_freq: steps between eigendecompositions (default 100).
      kl_clip: KL clipping parameter; None disables scaling (default 0.001).
      lr: learning rate used in the KL-clip scale (default 0.1).
      use_eigen_decomp: eigendecomposition method if True, else damped
        inverses (default None -> per-dim 'auto' dispatch; mutually
        consistent with ``inverse_method`` — contradictory combinations
        raise).
      inverse_method: 'auto' (the default — per-factor-dim dispatch:
        the eigen path with the warm-start polish where it wins, dims
        <= ``auto_eigen_max_dim``; ``auto_large_method`` damped inverses
        above, where the fp32 polish matmuls blow up — measured 41x at
        flagship 4609-dim factors, PERF.md round 3/4. One default that
        is fast at every scale, the analogue of the reference's one
        eigen default serving all dims, kfac/layers/base.py:432-441),
        'eigen' (same as ``use_eigen_decomp=True`` — every factor),
        'cholesky' (XLA Cholesky + triangular solves, the reference's
        non-eigen method) or 'newton' (matmul-only Newton–Schulz, Pallas
        VMEM-resident on TPU — see ops.pallas_kernels).
      auto_eigen_max_dim: largest factor dim the 'auto' dispatch keeps
        on the eigen path (default 640 — the measured v5e crossover
        region: warm polish wins 3-5x over cold eigh at CIFAR-class
        dims <= 577 and costs seconds per firing at 2305+; PERF.md).
        Layers with one side above and one below mix representations;
        any such *split* layer preconditions as the reference's
        non-eigen operator ``(G+λI)^{-1} ⊗ (A+λI)^{-1}`` (damping
        semantics note: PARITY.md; dispatch: linalg.precondition_dispatch).
      auto_large_method: 'cholesky' (default) or 'newton' — the damped
        inverse used above the cutoff in 'auto' mode.
      inv_lowrank_rank: rank of the randomized truncated
        eigendecomposition path (r19, *Randomized K-FACs*
        arXiv:2206.15397). 0 (default) = off — the exact per-dim
        dispatch above, bit-identical. With ``r > 0``, dense factor
        dims ``>= inv_lowrank_dim_threshold`` decompose as a rank-r
        truncated eigenpair instead of a full O(d^3) factorization:
        a Gaussian range-finder sketch seeds the basis once, and each
        firing refreshes it with one subspace iteration plus the
        warm-start polish (``ops.linalg.lowrank_eigh`` — r·d^2 matmul
        work, carried basis converges across windows). Preconditioning
        consumes the truncated (Q, d) plus the damping-only complement
        (``I/λ`` on the discarded tail — full-rank correct, tail
        curvature regularized to the damping floor), so the per-step
        eigen contractions are r-thin too. The truncated slots replace
        the engaged sides' dense representation (a KAISA-style
        memory/compute trade-off knob, arXiv:2107.01739 — state for an
        engaged side is r·d instead of d^2); the exact path stays the
        default and the parity oracle. ``r`` must be < every engaged
        dim (validated at registration — rank >= dim is a hard error,
        never a silent fallback). Composes with ``inv_pipeline_chunks``
        (the LPT chunk planner switches the engaged buckets' cost
        model to r·dim^2), ``inv_staleness`` and the bf16 pipeline.
      inv_lowrank_dim_threshold: smallest dense factor dim the
        low-rank path engages (default 2048 — transformer-scale
        factors, where the exact decomposition is the measured
        fired-step wall; BENCH_r09/r14). Ignored at
        ``inv_lowrank_rank=0``.
      eigh_method: backend for the eigen path's decompositions:
        'auto' (default — the warm-start matmul-only basis polish,
        ops.linalg.eigh_polish, seeded from the previous firing's
        eigenbasis carried in the state; falls back to 'xla' where no
        previous basis exists, e.g. factor-only checkpoint restore),
        'warm' (always polish), 'xla' (the backend eigh every firing)
        or 'jacobi' (vectorized parallel cyclic Jacobi,
        ops.linalg.jacobi_eigh). On TPU 'auto' is both faster and
        data-independent in runtime: the backend eigh's iterative
        while-loops run ~5x longer on trained covariance factors than
        on identity-seeded ones (PERF.md §6).
      eigh_polish_iters: fixed iteration count for the warm polish
        (default 8 — ~1e-3 worst-case preconditioner error at EWMA
        drift rates, measured indistinguishable from 16 iters on the
        workload-level convergence study while saving ~1.5 ms/iter on
        the tracked config at inv_freq=10; pass 16 for the ~1e-5
        tracking regime. Sweep data: PERF.md round 3; see
        ops.linalg.eigh_polish).
      newton_iters: iteration cap for 'newton' (the loop exits early on
        a 1e-5 residual; ~log2(cond)+6 iterations are used in practice).
      factor_dtype: dtype for factor running averages (default fp32; pass
        ``jnp.bfloat16`` for bf16 factor storage/comm — the analogue of the
        reference's keep-autocast-dtype policy, README.md:150-160).
      factor_compute_dtype: input dtype/precision for the covariance
        matmuls (accumulation is always fp32). Default None uses the
        backend's native matmul precision — on TPU that is bf16 inputs
        with fp32 accumulation (~4e-3 relative covariance error), the
        production fast path. ``jnp.float32`` requests *strict* fp32
        (inputs cast + ``Precision.HIGHEST``; numerics parity with the
        reference's fp32 factors at ~2x covariance cost on TPU).
        ``jnp.bfloat16`` makes the bf16 fast path explicit — the
        analogue of the reference's fp16 factor mode (``--fp16``,
        launch_node_torch_imagenet.sh:73-87) with better accumulation.
        See ops.factors.get_cov for the measured numbers.
      factor_batch_fraction: fraction of the (per-device) batch used for
        the A/G covariance statistics (default 1.0 = reference parity:
        the whole batch). Values < 1 keep ``ceil(B * f)`` evenly-strided
        rows of every capture before the factor contraction — an
        estimator of the same expectations (every covariance here
        normalizes by its own row count; strided, not a head slice, so
        ordered batches still contribute across the batch), thinning
        *within* the batch exactly as the reference's production cadence
        thins
        *across* steps (factors from one batch in 50,
        launch_node_torch_imagenet.sh:73-87). The factor phase's cost
        (patch materialization + contraction, the dominant K-FAC
        overhead at CIFAR scale — PERF.md roofline) scales with f.
        Gradients and preconditioning always see the full batch.
      capture_dtype: dtype for captured activations ('a'). Default
        'auto' = bf16 on TPU (what the covariance matmul keeps anyway;
        halves capture + im2col patch traffic — see KFACCapture), fp32
        passthrough elsewhere and under strict
        ``factor_compute_dtype=float32`` parity. ``None`` = always
        passthrough; explicit dtype forces the cast. Reference parity:
        hooks capture the autocast dtype under AMP
        (kfac/layers/base.py:385).
      inv_dtype: dtype for stored inverses (default fp32; decompositions
        always *computed* in fp32, reference base.py:432-441).
      precond_compute_dtype: input dtype for the per-step precondition
        contractions (``inverse · grad``), mirroring
        ``factor_compute_dtype``'s contract — accumulation is always
        fp32, and the damping quotient on the eigen path stays fp32.
        Default None is the legacy path (operands upcast to fp32,
        backend-native matmul precision) and is bit-identical to the
        pre-knob behavior. ``jnp.bfloat16`` runs bf16 operands with
        fp32 accumulation — the MXU fast path for the every-step
        ``G_inv @ grad @ A_inv`` matmuls that dominate the LM
        flagship's non-factor overhead (PERF.md r6); combined with
        ``inv_dtype=jnp.bfloat16`` the stored inverses are consumed
        *resident* (no fp32 upcast-on-read copy — the bandwidth lever
        when the step is HBM-bound on inverse reads). ``jnp.float32``
        requests strict fp32 (``Precision.HIGHEST``). Threaded through
        ``linalg.precondition_dispatch`` for every branch
        (eigen / baked-inverse / diagonal / mixed), single-chip and
        SPMD alike.
      precond_bucketing: batch same-shape dense layers' precondition
        matmuls into one vmapped kernel per shape group (default True —
        the r6 fast path). ``False`` restores the per-layer dispatch
        loop exactly — the escape hatch if a backend's batched
        dot_general ever tiles/accumulates differently from the
        unbatched matmul (bit-identity of the default-dtype bucketed
        path is pinned on the CPU test backend; on-TPU bit-identity is
        expected — vmap adds a batch dim, it does not reassociate a
        slice's contraction — but remains to be pinned on-chip).
      kfac_approx: weight-sharing Kronecker approximation policy
        (arXiv:2311.00636; see ``sharing.approx``). ``'expand'``
        (default) flattens every layer's shared sequence/patch axis
        into covariance rows — bit-identical to the pre-sharing code
        path (test-pinned). ``'reduce'`` engages the automatic
        by-module-kind policy: sequence/patch-shared Denses (attention
        q/k/v/o, MLP in/out) and patch-embedding convs reduce over the
        shared axis BEFORE the covariance (activations averaged,
        output-grads summed — Eq. 22's bias-column-exactly-1
        convention), a factor-T cheaper factor update with matching
        quality on transformer/ViT workloads (PERF.md r13); everything
        else stays expand. A ``{pattern: 'expand'|'reduce'}`` dict
        gives explicit per-layer control (loud validation). Factor
        DIMS are approximation-invariant, so the state layout, KAISA
        buckets and chunk plans are identical under any setting — the
        choice is static program structure (zero retraces,
        test-pinned).
      tied_embeddings: capture ``Embed.attend`` call sites (the tied
        in/out decoder, flax's form of the reference
        register_shared_module pair, preconditioner.py:404-470) so
        both uses of a tied embedding weight contribute statistics to
        ONE factor pair with ONE inverse entry: A gains the attend
        output-grads' diagonal vocab covariance, G the attend inputs'
        covariance. ``None`` (default) follows the sharing subsystem —
        on with any non-expand ``kfac_approx``, off under the pure
        default (which keeps the all-default path bit-identical:
        lookup-only statistics, the historical behavior where the
        attend site contributed gradient but no statistics). State
        layout is unchanged either way (additive statistics only —
        MIGRATION.md).
      skip_layers: module names/classes to skip (case-insensitive, prunes
        subtrees).
      trainable: optional predicate ``trainable(module_path) -> bool``
        marking which layers actually train — frozen layers (e.g. an
        optax.masked fine-tune) get plain gradients and NO factor/
        inverse work (reference module_requires_grad,
        kfac/layers/__init__.py:38-40).
      symmetry_aware_comm: communicate only ~half of each (symmetric)
        factor matrix — a gather-free rectangular triangular packing
        (ops.factors.pack_symmetric) before the allreduce (reference
        kfac/layers/base.py:120-125). Worth it when factor averaging
        crosses hosts (DCN-bound); on-chip the pack/unpack mask-and-
        concat work usually costs more than the halved bytes.
      assignment_strategy: 'compute' (n^3 cost) or 'memory' (n^2) for the
        LPT work balancer (reference preconditioner.py:625-628).
      comm_method / grad_worker_fraction: see CommMethod; consumed by the
        distributed step builder in ``parallel.distributed``.
      collect_metrics: carry an on-device metrics pytree in the state
        (``state['metrics']``, see observability.metrics) updated by
        the step — damping, KL-clip ν, grad/preconditioned-grad norms,
        per-bucket precondition norms, factor/inverse firing counts,
        eigenvalue-floor clips, non-finite events. All traced scalar
        updates: no host syncs; the host drains asynchronously (the
        engine's JSONL sink). Default False is bit-identical to the
        pre-observability step — the same discipline as
        ``precond_compute_dtype=None`` (test-pinned).
      inv_pipeline_chunks: pipeline the per-firing inverse work across
        the cadence window (default 1 = reference parity, bit-identical:
        the whole factor set decomposes in one firing step). With
        ``k > 1`` the inverse work items (the same-shape bucket stacks
        the precondition/linalg paths already form, plus the grouped/
        diagonal layers) are greedy-bin-packed into ``k`` cost-balanced
        chunks on a dim^3 proxy (:meth:`inverse_chunk_plan`), and the
        engine fires chunk ``j`` on step ``t = j * inv_update_freq/k``
        of each window instead of firing everything at the window head —
        smearing the decomposition spike (measured 4x the non-factor
        step on the xl LM flagship, PERF.md r5) into ``k`` smaller ones.
        Each chunk phase is its own statically-compiled program variant
        (``KFAC.step(inv_chunk=j)`` /
        ``DistributedKFAC.build_train_step``'s variant cache) — cadence
        stays static program structure, no retraces (PERF.md pitfalls
        2-3). Semantics: every factor still refires every
        ``inv_update_freq`` steps; chunks fired mid-window see factors
        up to ``inv_update_freq * (k-1)/k`` steps FRESHER than the
        window head (strictly less stale than the reference), but
        layer inverses are no longer simultaneous across chunks — with
        factors frozen across a window, one full pipelined window is
        bit-identical to a monolithic firing (test-pinned). The eigen
        warm-start carry is unaffected: each factor's previous basis is
        per-factor state updated only when its own chunk fires, so
        chunking is NOT rejected under ``inverse_method='eigen'`` /
        warm polish (documented decision, ISSUE r9). Constraints:
        ``k >= 1``, ``k`` must divide ``inv_update_freq``, and ``k``
        may not exceed the model's inverse work-item count (validated
        at registration).
      inv_pipeline_costs: optional ``{factor_dim: measured_ms}``
        refinement for the chunk bin-packing — the per-bucket
        ``bucket_parts`` ms of a flagship firing leg
        (FLAGSHIP_LM_*.jsonl) in place of the default
        ``count * dim^3`` proxy. Must cover EVERY dense factor dim of
        the model (validated at plan time): ms and the dim^3 proxy are
        different units and a partial dict would silently un-balance
        the packing.
      deferred_factor_reduction: accumulate factor-statistic
        contributions LOCALLY on factor steps and apply them to the
        running averages only at the cadence-window boundary where the
        inverses consume them (default False = reference parity: the
        EWMA advances — and, under SPMD, the cross-replica factor
        ``pmean`` fires — on every factor step). The decayed EMA is
        linear, so the deferred form is mathematically exact at every
        consumption point: with per-step decay ``α_i`` the boundary
        update ``F ← (Π α_i) · F + Σ_i (Π_{j>i} α_j)(1-α_i) · c_i``
        equals the per-step recursion, and (under SPMD)
        ``pmean(Σ w_i c_i) = Σ w_i pmean(c_i)`` — equal up to fp
        associativity (the summation order differs). The win is on the
        mesh: the per-factor-step collective on the critical path
        collapses to ONE bucketed reduction per cadence window
        (``kfac/comm/factor_reduce``; arXiv:2107.06533's smart-overlap
        framing, ROADMAP item 2). Static-cadence only — the reduce is
        static program structure like ``inv_chunk`` (the engine passes
        ``factor_reduce=True`` on window-head steps). Scope notes:
        mid-window chunk firings (``inv_pipeline_chunks > 1``) see the
        factors as of the last window-head reduction (the staleness
        profile of ``inv_staleness=1`` rather than r9's
        fresher-mid-window factors); with ``nonfinite_guard`` the
        finiteness check moves to the reduce point's post-average
        candidate (collective-safe, unchanged), so a poisoned window
        is skipped WHOLE — the accumulator resets either way.
      hierarchical_reduce: two-level factor reduction for multi-slice
        meshes (r20, SPMD-only; mutually exclusive with
        ``deferred_factor_reduction``). Factor contributions are
        ``pmean``-ed WITHIN each slice (over ICI) on every factor step
        and folded into a per-slice accumulator; the inter-slice
        (DCN) half of the mean is deferred to ONE bucketed reduce per
        cadence window (``kfac/comm/factor_reduce_dcn``) — exact by
        the same EMA-linearity argument as the deferred form, since
        ``pmean_slices(pmean_intra(c)) = pmean_all(c)``. Requires a
        ``multislice.make_multislice_mesh`` mesh with > 1 slice;
        :class:`KFAC` itself (single-chip, no mesh) raises on step.
      inv_staleness: 0 (default) or 1. At 1, the decompositions
        consumed during cadence window ``w+1`` are computed from
        factors FROZEN at the end of window ``w`` (a snapshot carried
        in ``state['frozen_factors']``, refreshed on window-head
        steps) and fired across the window's plain steps: chunk ``j``
        fires at phase ``j * inv_update_freq/k + 1`` instead of r9's
        ``j * stride`` (with ``inv_pipeline_chunks == 1`` the whole
        firing runs as one chunk at phase 1). Because the firing reads
        the snapshot, it has NO data dependency on the firing step's
        forward/backward or factor update — XLA can overlap the eigh
        with the step's compute and collectives instead of serializing
        behind them (arXiv:2206.15143's off-critical-path inverses),
        and the +1 phase offset keeps the spike off the window-head
        step that pays the factor reduction. Preconditioning applies a
        one-window-stale inverse (the monolithic k=1 staleness
        profile; strictly staler than r9's mid-window chunks) — gate
        promotion on a convergence A/B exactly like r9's (PERF.md
        r14). Step 0 still fires monolithically from the fresh
        snapshot (slots are zero-seeded). Static-cadence only.
        Requires ``inv_update_freq / inv_pipeline_chunks >= 2`` so the
        shifted phases stay inside the window.
      nonfinite_guard: skip the factor EWMA update when the candidate
        factors are non-finite (a NaN/Inf gradient/capture batch would
        otherwise poison the running averages forever — EWMA keeps
        NaN). The skip is on-device (``where`` on a finiteness flag,
        collective-safe: it checks the post-average candidates) and
        counted in ``metrics['nonfinite_skips']`` when metrics are on.
        Scope: this protects the FACTOR STATISTICS only — the same
        step's gradients still flow through precondition and whatever
        optimizer update the caller applies. For a whole-step skip of
        params/optimizer on non-finite gradients, use the dynamic
        loss-scale path (``build_train_step(loss_scale='dynamic')`` —
        GradScaler parity), which composes with this guard.
        Default False = reference behavior (no guard).
    """

    def __init__(self, model: nn.Module, *,
                 damping: float = 0.001,
                 factor_decay: float = 0.95,
                 factor_update_freq: int = 10,
                 inv_update_freq: int = 100,
                 kl_clip: float | None = 0.001,
                 lr: float = 0.1,
                 use_eigen_decomp: bool | None = None,
                 inverse_method: str | None = None,
                 auto_eigen_max_dim: int = 640,
                 auto_large_method: str = 'cholesky',
                 inv_lowrank_rank: int = 0,
                 inv_lowrank_dim_threshold: int = 2048,
                 eigh_method: str = 'auto',
                 eigh_polish_iters: int = 8,
                 newton_iters: int = 100,
                 factor_dtype: Any = None,
                 factor_compute_dtype: Any = None,
                 factor_batch_fraction: float = 1.0,
                 capture_dtype: Any = 'auto',
                 inv_dtype: Any = jnp.float32,
                 precond_compute_dtype: Any = None,
                 precond_bucketing: bool = True,
                 inv_pipeline_chunks: int = 1,
                 inv_pipeline_costs: dict | None = None,
                 deferred_factor_reduction: bool = False,
                 hierarchical_reduce: bool = False,
                 inv_staleness: int = 0,
                 kfac_approx: Any = 'expand',
                 tied_embeddings: bool | None = None,
                 skip_layers: str | Sequence[str] | None = None,
                 trainable: Any = None,
                 symmetry_aware_comm: bool = False,
                 assignment_strategy: str = 'compute',
                 comm_method: CommMethod = CommMethod.COMM_OPT,
                 grad_worker_fraction: float = 0.25,
                 collect_metrics: bool = False,
                 nonfinite_guard: bool = False,
                 fused_factor_contraction: bool = False,
                 fused_precondition: bool = False,
                 verbose: bool = False):
        if factor_update_freq < 1 or inv_update_freq < 1:
            raise ValueError('update frequencies must be >= 1')
        if inv_update_freq % factor_update_freq != 0:
            warnings.warn(
                'inv_update_freq is not a multiple of factor_update_freq: '
                'some inverse updates will reuse stale factors '
                f'({inv_update_freq=} {factor_update_freq=})')
        if inv_pipeline_chunks < 1:
            raise ValueError(
                f'{inv_pipeline_chunks=} must be >= 1')
        if inv_pipeline_chunks > 1:
            if inv_update_freq % inv_pipeline_chunks != 0:
                raise ValueError(
                    'inv_pipeline_chunks must divide inv_update_freq '
                    'so chunk phases land on whole steps '
                    f'({inv_pipeline_chunks=} {inv_update_freq=})')
            stride = inv_update_freq // inv_pipeline_chunks
            if stride % factor_update_freq != 0:
                warnings.warn(
                    'inv_update_freq/inv_pipeline_chunks is not a '
                    'multiple of factor_update_freq: some chunk '
                    'firings will reuse stale factors '
                    f'({inv_update_freq=} {inv_pipeline_chunks=} '
                    f'{factor_update_freq=})')
        if inv_staleness not in (0, 1):
            raise ValueError(
                f'{inv_staleness=} must be 0 or 1 (one-window-stale '
                'off-critical-path inverses; deeper staleness is not '
                'supported)')
        if inv_staleness == 1:
            k = max(1, inv_pipeline_chunks)
            if inv_update_freq % k != 0 or inv_update_freq // k < 2:
                raise ValueError(
                    'inv_staleness=1 fires chunk j at phase '
                    'j*(inv_update_freq/inv_pipeline_chunks)+1 of each '
                    'window, which needs inv_update_freq/'
                    'inv_pipeline_chunks >= 2 so the shifted phases '
                    f'stay inside the window ({inv_update_freq=} '
                    f'{inv_pipeline_chunks=})')
        if assignment_strategy not in ('compute', 'memory'):
            raise ValueError("assignment_strategy must be 'compute' or "
                             "'memory'")
        if (capture_dtype == 'auto' and factor_compute_dtype is not None
                and jnp.dtype(factor_compute_dtype).itemsize
                > jnp.dtype(jnp.bfloat16).itemsize):
            # A strict high-precision factor request (fp32, fp64, ...)
            # implies captures at least that wide: a bf16 capture would
            # discard the precision the high-precision covariance
            # contraction exists to keep (ADVICE r3: the old gate only
            # matched fp32, leaking bf16 captures under fp64).
            capture_dtype = None
        # Weight-sharing approximation policy (sharing.approx,
        # arXiv:2311.00636): 'expand' (default, bit-identical to the
        # pre-sharing code path), 'reduce' (automatic by-module-kind:
        # sequence/patch-shared Denses + patch-embed convs reduce over
        # the shared axis before the covariance — a factor-T cheaper
        # factor update), or a {pattern: approx} dict for explicit
        # per-layer control. Resolved per layer at init() and carried
        # in the LayerSpec registry (static program structure).
        from distributed_kfac_pytorch_tpu.sharing import approx as _approx
        if isinstance(kfac_approx, str) or kfac_approx is None:
            if kfac_approx not in (None, 'expand', 'reduce'):
                raise ValueError(
                    f"kfac_approx must be 'expand', 'reduce' or a "
                    f'{{pattern: approx}} dict, got {kfac_approx!r}')
        elif not isinstance(kfac_approx, dict):
            raise ValueError(
                f"kfac_approx must be 'expand', 'reduce' or a dict, "
                f'got {type(kfac_approx).__name__}')
        self.kfac_approx = kfac_approx if kfac_approx is not None \
            else 'expand'
        self._approx_mod = _approx
        # Tied-embedding handling (one factor pair + one inverse for an
        # in/out-tied Embed; the attend call site's statistics join the
        # lookup's). None follows the sharing subsystem: engaged when
        # the setting actually names 'reduce' anywhere, off otherwise —
        # so 'expand', AND an all-expand dict like {'embed': 'expand'},
        # keep the bit-identical pre-sharing path (an explicit
        # per-layer pin must not silently change the capture program).
        if tied_embeddings is None:
            if isinstance(self.kfac_approx, dict):
                tied_embeddings = any(v == 'reduce'
                                      for v in self.kfac_approx.values())
            else:
                tied_embeddings = self.kfac_approx == 'reduce'
        self.tied_embeddings = bool(tied_embeddings)
        self.capture = KFACCapture(model, skip_layers=skip_layers,
                                   capture_dtype=capture_dtype,
                                   trainable=trainable,
                                   tied_embeddings=self.tied_embeddings)
        self.model = model
        self.damping = damping
        self.factor_decay = factor_decay
        self.factor_update_freq = factor_update_freq
        self.inv_update_freq = inv_update_freq
        self.kl_clip = kl_clip
        self.lr = lr
        if inverse_method is None:
            if use_eigen_decomp is None:
                inverse_method = 'auto'
            else:
                inverse_method = ('eigen' if use_eigen_decomp
                                  else 'cholesky')
        if inverse_method not in ('auto', 'eigen', 'cholesky', 'newton'):
            raise ValueError(
                "inverse_method must be 'auto', 'eigen', 'cholesky' or "
                f"'newton', got {inverse_method!r}")
        if use_eigen_decomp is not None and (
                inverse_method == 'auto'
                or use_eigen_decomp != (inverse_method == 'eigen')):
            raise ValueError(
                f'{use_eigen_decomp=} contradicts {inverse_method=}; '
                'set one or the other')
        if auto_large_method not in ('cholesky', 'newton'):
            raise ValueError(
                "auto_large_method must be 'cholesky' or 'newton', "
                f'got {auto_large_method!r}')
        if eigh_method not in ('auto', 'xla', 'jacobi', 'warm'):
            raise ValueError(
                "eigh_method must be 'auto', 'xla', 'jacobi' or 'warm', "
                f'got {eigh_method!r}')
        self.inverse_method = inverse_method
        self.use_eigen_decomp = inverse_method == 'eigen'
        self.auto_eigen_max_dim = auto_eigen_max_dim
        self.auto_large_method = auto_large_method
        inv_lowrank_rank = int(inv_lowrank_rank)
        inv_lowrank_dim_threshold = int(inv_lowrank_dim_threshold)
        if inv_lowrank_rank < 0:
            raise ValueError(
                f'{inv_lowrank_rank=} must be >= 0 (0 disables the '
                'randomized low-rank inverse path)')
        if inv_lowrank_rank > 0 and inv_lowrank_dim_threshold < 2:
            raise ValueError(
                f'{inv_lowrank_dim_threshold=} must be >= 2 with '
                'inv_lowrank_rank > 0 (a rank-r truncation of a '
                'dim < 2 factor cannot satisfy rank < dim)')
        self.inv_lowrank_rank = inv_lowrank_rank
        self.inv_lowrank_dim_threshold = inv_lowrank_dim_threshold
        self.eigh_method = eigh_method
        self.eigh_polish_iters = eigh_polish_iters
        self.newton_iters = newton_iters
        if not 0.0 < factor_batch_fraction <= 1.0:
            raise ValueError(
                f'{factor_batch_fraction=} must be in (0, 1]')
        self.factor_batch_fraction = factor_batch_fraction
        self.factor_dtype = factor_dtype
        self.factor_compute_dtype = factor_compute_dtype
        self.inv_dtype = inv_dtype
        self.precond_compute_dtype = precond_compute_dtype
        self.precond_bucketing = precond_bucketing
        self.inv_pipeline_chunks = inv_pipeline_chunks
        self.inv_pipeline_costs = (dict(inv_pipeline_costs)
                                   if inv_pipeline_costs else None)
        if hierarchical_reduce and deferred_factor_reduction:
            raise ValueError(
                'hierarchical_reduce and deferred_factor_reduction are '
                'mutually exclusive: hierarchical reduce already '
                'defers the (inter-slice DCN) half of the factor '
                'reduction to the window boundary, and its intra-slice '
                'ICI pmean must fire every factor step')
        self.deferred_factor_reduction = bool(deferred_factor_reduction)
        self.hierarchical_reduce = bool(hierarchical_reduce)
        self.inv_staleness = int(inv_staleness)
        self.symmetry_aware_comm = symmetry_aware_comm
        self.assignment_strategy = assignment_strategy
        self.comm_method = comm_method
        self.grad_worker_fraction = grad_worker_fraction
        self.collect_metrics = collect_metrics
        self.nonfinite_guard = nonfinite_guard
        # r21 fused hot-path kernels (ops.pallas_kernels): default-off
        # knobs; with a knob on, eligible work runs the Pallas kernel
        # when the once-per-process parity probe passes, and the stock
        # XLA path otherwise (a recorded 'pallas_fallback' event — never
        # a silent degrade). Off is bit-identical to the historical
        # program.
        self.fused_factor_contraction = bool(fused_factor_contraction)
        self.fused_precondition = bool(fused_precondition)
        self.verbose = verbose
        self._specs: dict[str, Any] | None = None

    def __repr__(self) -> str:
        """Hyperparameter dump (reference KFAC.__repr__,
        preconditioner.py:265-292)."""
        fields = ('damping', 'factor_decay', 'factor_update_freq',
                  'inv_update_freq', 'kl_clip', 'lr', 'inverse_method',
                  'auto_eigen_max_dim', 'auto_large_method',
                  'inv_lowrank_rank', 'inv_lowrank_dim_threshold',
                  'eigh_method', 'eigh_polish_iters', 'newton_iters',
                  'factor_batch_fraction', 'factor_dtype',
                  'factor_compute_dtype', 'inv_dtype',
                  'precond_compute_dtype', 'precond_bucketing',
                  'inv_pipeline_chunks',
                  'deferred_factor_reduction', 'hierarchical_reduce',
                  'inv_staleness',
                  'kfac_approx', 'tied_embeddings',
                  'symmetry_aware_comm',
                  'assignment_strategy', 'comm_method',
                  'grad_worker_fraction', 'collect_metrics',
                  'nonfinite_guard', 'fused_factor_contraction',
                  'fused_precondition')
        lines = [f'  {name}: {getattr(self, name)!r}' for name in fields]
        n_layers = (len(self._specs) if self._specs is not None
                    else '<uninitialized>')
        lines.append(f'  registered_layers: {n_layers}')
        return 'KFAC(\n' + '\n'.join(lines) + '\n)'

    # ------------------------------------------------------------------
    # Per-dim inverse dispatch
    # ------------------------------------------------------------------

    def method_for_dim(self, dim: int) -> str:
        """Decomposition method for a dense factor of this dimension.

        'auto' dispatches per dim (eigen below ``auto_eigen_max_dim``,
        ``auto_large_method`` above — the measured v5e crossover,
        PERF.md); global modes return themselves. Host-side, static:
        the dispatch is baked into the trace, so it costs nothing at
        runtime and the single-chip and SPMD paths share it (VERDICT r3
        asks #1/#7).

        The r19 low-rank knob sits in FRONT of the base dispatch:
        with ``inv_lowrank_rank > 0``, any dense dim at or above
        ``inv_lowrank_dim_threshold`` resolves to ``'lowrank'`` — the
        randomized truncated eigendecomposition — regardless of the
        base method (the knob exists to replace whatever the large-dim
        path was; at rank 0 the dispatch is byte-identical to r18).
        """
        if (self.inv_lowrank_rank > 0
                and dim >= self.inv_lowrank_dim_threshold):
            return 'lowrank'
        if self.inverse_method == 'auto':
            return ('eigen' if dim <= self.auto_eigen_max_dim
                    else self.auto_large_method)
        return self.inverse_method

    def lowrank_rank_for(self, dim: int) -> int | None:
        """The truncation rank for a dim, or None where the exact path
        runs — the cost-model hook the r9/r14 chunk planners feed to
        ``linalg.decomposition_cost(dim, rank=...)``."""
        return (self.inv_lowrank_rank
                if self.method_for_dim(dim) == 'lowrank' else None)

    def _side_methods(self, spec, a_dim: int, g_dim: int
                      ) -> tuple[str | None, str | None]:
        """(A-side, G-side) methods for one layer; diagonal A -> None;
        grouped convs -> (None, None) (their per-group block stacks run
        a batched damped Cholesky, outside the dense per-dim dispatch —
        the blocks are tiny, so eigen warm-start bookkeeping would cost
        more than it saves)."""
        if spec.kind == CONV2D_GROUPED:
            return None, None
        ma = (None if spec.kind == EMBEDDING
              else self.method_for_dim(a_dim))
        return ma, self.method_for_dim(g_dim)

    # ------------------------------------------------------------------
    # Pipelined inverse firing: chunk planning
    # ------------------------------------------------------------------

    @property
    def pipelined_firing(self) -> bool:
        """True when the in-window chunk-firing machinery is engaged:
        ``inv_pipeline_chunks > 1`` (r9), or ``inv_staleness == 1`` —
        which chunk-fires even a single chunk mid-window from the
        frozen snapshot (at ``k == 1`` the plan is one chunk holding
        every work item, so the per-firing program keeps the
        monolithic shape)."""
        return self.inv_pipeline_chunks > 1 or self.inv_staleness == 1

    def inverse_chunk_items(self, factors: dict
                            ) -> list[tuple[tuple, float]]:
        """Cost-weighted inverse work items for pipelined firing.

        One item per dense factor matrix (``('mat', layer, 'A'|'G')``
        — the finest unit the bucketed eigh/inverse paths can regroup:
        within a chunk, same-dim fired matrices still stack into one
        vmapped kernel via ``_size_buckets``, so chunking never changes
        a matrix's decomposition, only when it runs), one per
        grouped-conv layer (its per-group block stacks), one per
        diagonal-A embedding layer. Matrix granularity — rather than
        whole same-dim buckets — is what lets the bin-packer hit the
        <= 1.5x-of-ideal balance bound on factor sets whose largest
        bucket alone exceeds ``total/k`` (the xl LM's 18x4096^2 bucket
        is 1.9x the k=4 ideal; test-pinned in
        tests/test_inv_pipeline.py). Costs use the ``linalg``
        decomposition proxy (``dim^3``), or — when
        ``inv_pipeline_costs`` is given — measured per-bucket
        ``bucket_parts`` ms split evenly over each bucket's matrices.
        Measured ms and the dim^3 proxy are DIFFERENT UNITS, so a
        measurement dict must cover **every dense factor dim** (a
        partial one raises: mixing a measured 531.8 ms next to a
        proxied 1024^3 would weight the genuinely heaviest bucket
        ~1e7x too cheap and silently un-balance the plan); the tiny
        grouped/diagonal proxy costs are rescaled into the measured
        unit by the fitted ms-per-dim^3 factor.
        """
        from distributed_kfac_pytorch_tpu.ops.linalg import (
            decomposition_cost,
        )
        dense_count: dict[int, int] = {}
        for name, spec in self.specs.items():
            if spec.kind in (CONV2D_GROUPED,):
                continue
            f = factors[name]
            if spec.kind != EMBEDDING:
                a = int(f['A'].shape[-1])
                dense_count[a] = dense_count.get(a, 0) + 1
            g = int(f['G'].shape[-1])
            dense_count[g] = dense_count.get(g, 0) + 1
        measured = self.inv_pipeline_costs or {}
        # One global cost unit: proxy dim^3, or measured ms when a
        # complete measurement is supplied. proxy_scale converts the
        # non-dense proxy costs into the measured unit.
        proxy_scale = measured_unit_scale(measured, dense_count,
                                          'dense factor dim')

        def unit_cost(dim: int) -> float:
            if dim in measured:
                return float(measured[dim]) / dense_count[dim]
            # r19: low-rank buckets fire at r·dim^2, not dim^3 — the
            # plan must weigh them accordingly or every mixed window
            # un-balances by dim/r.
            return decomposition_cost(dim,
                                      rank=self.lowrank_rank_for(dim))

        items: list[tuple[tuple, float]] = []
        for name, spec in self.specs.items():
            f = factors[name]
            a_dim = int(f['A'].shape[-1])
            g_dim = int(f['G'].shape[-1])
            if spec.kind == CONV2D_GROUPED:
                ng = int(f['A'].shape[0])
                items.append((('grouped', name),
                              proxy_scale
                              * (ng * decomposition_cost(a_dim)
                                 + ng * decomposition_cost(g_dim))))
                continue
            if spec.kind == EMBEDDING:
                # Elementwise reciprocal: O(dim), negligible next to any
                # dense decomposition but still a schedulable item.
                items.append((('diag', name), proxy_scale * a_dim))
            else:
                items.append((('mat', name, 'A'), unit_cost(a_dim)))
            items.append((('mat', name, 'G'), unit_cost(g_dim)))
        return items

    def inverse_chunk_plan(self, factors: dict) -> dict[tuple, int]:
        """Static item -> chunk assignment for ``inv_pipeline_chunks``.

        Greedy LPT bin-packing (``parallel.placement.load_balance``, the
        same balancer the KAISA work assignment uses) of the
        :meth:`inverse_chunk_items` onto ``k`` chunks. Deterministic
        (registration order + sorted dims), so every trace — and the
        single-chip vs SPMD paths — sees the identical plan. Raises if
        ``k`` exceeds the item count (more chunks than schedulable
        buckets cannot balance anything).
        """
        items = self.inverse_chunk_items(factors)
        k = self.inv_pipeline_chunks
        if k > len(items):
            raise ValueError(
                f'inv_pipeline_chunks={k} exceeds the {len(items)} '
                'inverse work items of this model (dense factor '
                'matrices + grouped/diagonal layers); lower it to at '
                f'most {len(items)}')
        return plan_inverse_chunks(items, k)

    # ------------------------------------------------------------------
    # Registration / state init
    # ------------------------------------------------------------------

    def init(self, rng, *args, init_model: nn.Module | None = None,
             **kwargs):
        """Init model variables and K-FAC state in one pass.

        Returns ``(variables, kfac_state)``; layer registration (the
        analogue of reference register_model, preconditioner.py:355-402)
        happens as a side effect of tracing the model. ``init_model``
        substitutes a structurally-identical single-device twin for the
        registration trace (see KFACCapture.init) — used by
        sequence-parallel models whose ring collectives only trace inside
        ``shard_map``.
        """
        variables, specs = self.capture.init(rng, *args,
                                             init_model=init_model,
                                             **kwargs)
        # Resolve the weight-sharing approximation per layer and bake
        # it into the registry (sharing.annotate_specs) — after this,
        # every factor-math consumer reads spec.kfac_approx. The
        # capture object keeps its own unannotated copy (it only needs
        # call/tied counts for pairing).
        self._specs = self._approx_mod.annotate_specs(specs,
                                                      self.kfac_approx)
        specs = self._specs
        if self.verbose:
            for name, spec in specs.items():
                print(f'Registered {name}: {spec.kind} '
                      f'(bias={spec.has_bias}, calls={spec.num_calls}, '
                      f'approx={spec.kfac_approx}'
                      + (f', tied_calls={spec.tied_calls}'
                         if spec.tied_calls else '') + ')')
            for name, reason in self.capture.skipped_modules.items():
                print(f'Skipped {name}: {reason}')
        state = self.init_state(variables['params'])
        return variables, state

    @property
    def specs(self):
        if self._specs is None:
            raise ValueError('call init() first')
        return self._specs

    def approx_summary(self) -> dict[str, str]:
        """{layer name: resolved approx} for run provenance.

        The per-layer map the observability meta records (the JSONL
        ``kind='meta'`` record the CLIs append after registration) —
        tied registrations are labeled ``<approx>+tied``. See
        ``sharing.approx_summary``.
        """
        return self._approx_mod.approx_summary(self.specs)

    def init_state(self, params) -> dict:
        """Fresh K-FAC state pytree for the registered layers.

        Factors start at identity — the reference seeds the running
        average with identity on the first update (base.py:389,416); with a
        functional state we materialize that seed up front (the first EWMA
        update then matches exactly). Eigen-path slots start at the exact
        eigendecomposition of those identity seeds (``Q = I, d = 1``) so
        the warm-start polish (eigh_method 'auto'/'warm') has a valid
        basis from step 0 — no cold-start eigh exists anywhere in the
        training path. Non-eigen inverse slots start as zeros; every slot
        is computed at step 0 before first use (0 % freq == 0).
        """
        factors, inverses = {}, {}
        for name, spec in self.specs.items():
            a_dim, g_dim = L.factor_shapes(spec, _get(params, spec.path))
            fdt = self.factor_dtype or jnp.float32
            idt = self.inv_dtype
            ma, mg = self._side_methods(spec, a_dim, g_dim)
            for which, m, dim in (('A', ma, a_dim), ('G', mg, g_dim)):
                if m == 'lowrank' and self.inv_lowrank_rank >= dim:
                    # Fail closed: a rank at or above the engaged dim
                    # cannot truncate anything — never silently fall
                    # back to the exact path (CI pins this error).
                    raise ValueError(
                        f'inv_lowrank_rank={self.inv_lowrank_rank} '
                        f'must be < the engaged factor dim {dim} '
                        f'(layer {name!r} side {which}; dims >= '
                        f'inv_lowrank_dim_threshold='
                        f'{self.inv_lowrank_dim_threshold} run the '
                        'randomized low-rank path) — lower the rank '
                        'or raise the threshold')
            # Mixed layers carry a firing-time-baked dense inverse for
            # their eigen-family side too (zero-seeded; step 0 fires
            # before first use) — see update_inverses.
            mixed = (spec.kind != EMBEDDING
                     and eigen_family(ma) != eigen_family(mg))

            def eigen_seed(dim: int, method: str):
                """Identity eigenpair seed. Low-rank sides carry a
                rectangular (dim, r) identity-column basis — orthonormal
                columns, a valid warm start for the subspace-refresh +
                polish from step 0 — and r unit eigenvalues."""
                r = (self.inv_lowrank_rank if method == 'lowrank'
                     else dim)
                return (jnp.eye(dim, r, dtype=idt),
                        jnp.ones((r,), idt))

            entry: dict[str, Any] = {}
            if spec.kind == CONV2D_GROUPED:
                ng = spec.feature_group_count
                factors[name] = {
                    'A': jnp.broadcast_to(jnp.eye(a_dim, dtype=fdt),
                                          (ng, a_dim, a_dim)),
                    'G': jnp.broadcast_to(jnp.eye(g_dim, dtype=fdt),
                                          (ng, g_dim, g_dim))}
                inverses[name] = {
                    'A_inv': jnp.zeros((ng, a_dim, a_dim), idt),
                    'G_inv': jnp.zeros((ng, g_dim, g_dim), idt)}
                continue
            if spec.kind == EMBEDDING:
                factors[name] = {'A': jnp.ones((a_dim,), fdt),
                                 'G': jnp.eye(g_dim, dtype=fdt)}
                entry['A_inv'] = jnp.zeros((a_dim,), idt)
            else:
                factors[name] = {'A': jnp.eye(a_dim, dtype=fdt),
                                 'G': jnp.eye(g_dim, dtype=fdt)}
                if eigen_family(ma):
                    entry['QA'], entry['dA'] = eigen_seed(a_dim, ma)
                    if mixed:
                        entry['A_inv'] = jnp.zeros((a_dim, a_dim), idt)
                else:
                    entry['A_inv'] = jnp.zeros((a_dim, a_dim), idt)
            if eigen_family(mg):
                entry['QG'], entry['dG'] = eigen_seed(g_dim, mg)
                if mixed:
                    entry['G_inv'] = jnp.zeros((g_dim, g_dim), idt)
            else:
                entry['G_inv'] = jnp.zeros((g_dim, g_dim), idt)
            inverses[name] = entry
        state = {'step': jnp.zeros((), jnp.int32),
                 'factors': factors, 'inverses': inverses,
                 # Pipelined-firing position: the next chunk index due
                 # (always 0 at init and after a monolithic firing;
                 # constant 0 under inv_pipeline_chunks=1). Checkpointed
                 # so resumed runs report where the pipeline stood;
                 # restore of pre-r9 bundles defaults it to 0
                 # (MIGRATION.md).
                 'inv_chunk_phase': jnp.zeros((), jnp.int32)}
        if self.deferred_factor_reduction:
            # Local pre-reduction accumulator (the decayed sum of
            # factor contributions since the last window-boundary
            # reduce) + the matching running decay product. Zero/one
            # seeds = "nothing accumulated" (the boundary update is
            # then the identity).
            state['factor_accum'] = jax.tree.map(jnp.zeros_like,
                                                 factors)
            state['accum_decay'] = jnp.ones((), jnp.float32)
        if self.inv_staleness:
            # The window-head factor snapshot the in-window firings
            # decompose (refreshed on factor_snapshot/inv_update
            # steps). Seeded with the identity-seeded factors — step 0
            # fires monolithically from a fresh snapshot before any
            # slot is consumed.
            state['frozen_factors'] = jax.tree.map(lambda x: x,
                                                   factors)
        if self.pipelined_firing:
            # Eager validation: the chunk count must not exceed the
            # model's inverse work buckets (raises with the bucket
            # count); the plan itself is recomputed statically at trace
            # time from the same shapes.
            self.inverse_chunk_plan(factors)
        if self.collect_metrics:
            state['metrics'] = obs_metrics.init_metrics(
                self.metric_bucket_keys(params))
        return state

    def metric_bucket_keys(self, params) -> list[str]:
        """Precondition shape-bucket keys for the metrics pytree.

        Derived by ``eval_shape`` over the same ``grads_to_matrix``
        transform the precondition pass runs, so the keys in the state
        structure match the runtime grouping exactly (one source of
        shape truth; trace-static).
        """
        keys: list[str] = []
        for name, spec in self.specs.items():
            sh = jax.eval_shape(
                lambda p, s=spec: L.grads_to_matrix(s, p),
                _get(params, spec.path)).shape
            key = obs_metrics.shape_key(sh)
            if key not in keys:
                keys.append(key)
        return keys

    def _tracked_factor_update(self, state: dict, captures: dict,
                               factor_decay) -> tuple[dict, jax.Array]:
        """Factor update + finiteness flag (metrics/guard path); the
        guard semantics live in :func:`guard_nonfinite_factors` (shared
        with the SPMD step)."""
        return guard_nonfinite_factors(
            self.update_factors(state, captures, factor_decay),
            state['factors'], self.nonfinite_guard)

    # NOTE: worker assignment (the reference's one-time deferred
    # _assign_workers, preconditioner.py:616-659) lives in
    # ``parallel.distributed.assign_work`` — the single LPT cost model
    # and placement path for the whole framework (round-1 review found a
    # parallel unused implementation here; it was removed).

    # ------------------------------------------------------------------
    # The pipeline stages (pure; called under jit)
    # ------------------------------------------------------------------

    def factor_contribs(self, captures: dict) -> dict:
        """Combined per-layer covariance contribution of one batch.

        The pre-EWMA half of :meth:`update_factors`: ``{name: {'A',
        'G'}}`` with the tied-embedding attend extras already folded in
        (single-chip captures are global, so no world rescale — cf.
        the SPMD path's g_scale). Shared by the eager EWMA path and the
        deferred-reduction accumulator so the contribution math cannot
        drift between them.
        """
        cdt = self.factor_compute_dtype
        captures = subsample_captures(captures, self.factor_batch_fraction)
        out = {}
        for name, spec in self.specs.items():
            a_new = L.compute_a_factor(spec, captures[name]['a'],
                                       compute_dtype=cdt)
            g_new = L.compute_g_factor(spec, captures[name]['g'],
                                       compute_dtype=cdt)
            extras = L.compute_tied_factor_extras(spec, captures[name],
                                                  compute_dtype=cdt)
            if extras is not None:
                # Tied embedding: the attend call site folds into the
                # SAME factor pair.
                a_new = a_new + extras['A_g2']
                g_new = g_new + extras['G_a']
            out[name] = {'A': a_new, 'G': g_new}
        return out

    # -------------------- r21 fused hot-path kernels ------------------

    def fused_contraction_active(self) -> bool:
        """True when the fused factor-contraction kernel should run:
        knob on AND the once-per-process parity probe passed (probe
        failure records a 'pallas_fallback' event and pins the stock
        XLA path for the process)."""
        return (self.fused_factor_contraction
                and pallas_kernels.fused_factor_ema_supported())

    def fused_precond_active(self) -> bool:
        """True when the fused bucketed-precondition kernel should run
        (knob on AND its probe passed) — see
        :meth:`fused_contraction_active`."""
        return (self.fused_precondition
                and pallas_kernels.fused_precondition_supported())

    def fused_factor_inputs(self, spec, entry: dict) -> dict:
        """Kernel inputs per side for the fused contraction+EMA kernel.

        Returns ``{side: (x2d, scale, has_bias)}`` for the fused-eligible
        sides of one layer (key absent → that side runs the stock
        contribution). Eligibility is STATIC per layer (kind, capture
        call count, factor dim): plain dense A/G and conv2d G factors
        with a single capture call whose ``x.T @ x`` form the kernel
        reproduces exactly; everything else — multi-call sums,
        'reduce'-approx layers, embeddings, grouped convs, conv2d A
        (which has its own patch-cov kernel upstream of get_cov) — keeps
        the per-layer stock path. Shared by the single-chip EMA,
        deferred-accumulator fold, and the SPMD contraction-only path so
        the eligibility rule cannot drift between them.
        """
        if spec.kfac_approx == KFAC_REDUCE:
            return {}
        out = {}
        max_dim = pallas_kernels.MAX_PALLAS_DIM
        if spec.kind == LINEAR:
            a_calls, g_calls = entry['a'], entry['g']
            if len(a_calls) == 1:
                x = F.collapse_batch_dims(a_calls[0])
                n = x.shape[1] + (1 if spec.has_bias else 0)
                if n <= max_dim:
                    out['A'] = (x, None, spec.has_bias)
            if len(g_calls) == 1:
                x = F.collapse_batch_dims(g_calls[0])
                if x.shape[1] <= max_dim:
                    out['G'] = (x, None, False)
        elif spec.kind == CONV2D:
            g_calls = entry['g']
            if len(g_calls) == 1 and g_calls[0].ndim == 4:
                g = g_calls[0]
                spatial = g.shape[1] * g.shape[2]
                x = g.reshape(-1, g.shape[-1])
                if x.shape[1] <= max_dim:
                    out['G'] = (x, float(x.shape[0]) * spatial * spatial,
                                False)
        return out

    def _fused_blend_factors(self, old_factors: dict, captures: dict,
                             alpha) -> dict:
        """Fused contraction+EMA blend of one batch into ``old_factors``.

        ``old_factors`` is either the running averages
        (:meth:`update_factors`) or the r14 deferred-reduction
        accumulator (:meth:`accumulate_factors`) — both apply the SAME
        ``α·old + (1-α)·new`` recursion, so one fused blend serves
        both. Eligible layer sides run the packed Pallas kernel
        (contraction + bias assembly + EMA in VMEM, only the symmetric
        triangle round-tripping HBM); ineligible sides run the stock
        contribution + :func:`F.update_running_avg`, so the result
        pytree matches the stock path layer for layer.
        """
        cdt = self.factor_compute_dtype
        interp = jax.default_backend() != 'tpu'
        captures = subsample_captures(captures, self.factor_batch_fraction)
        out = {}
        for name, spec in self.specs.items():
            fused = self.fused_factor_inputs(spec, captures[name])
            old = old_factors[name]
            res = {}
            for side in ('A', 'G'):
                if side not in fused:
                    continue
                x, scale, has_bias = fused[side]
                res[side] = pallas_kernels.fused_factor_ema(
                    x, old[side].astype(jnp.float32), alpha, scale=scale,
                    has_bias=has_bias, compute_dtype=cdt,
                    interpret=interp).astype(old[side].dtype)
            if len(res) < 2:
                # Stock path for the ineligible sides. Tied-embedding
                # extras only exist for EMBEDDING layers, which are
                # never fused — extras always fold into stock sides.
                extras = L.compute_tied_factor_extras(
                    spec, captures[name], compute_dtype=cdt)
                if 'A' not in res:
                    a_new = L.compute_a_factor(spec, captures[name]['a'],
                                               compute_dtype=cdt)
                    if extras is not None:
                        a_new = a_new + extras['A_g2']
                    res['A'] = F.update_running_avg(
                        a_new.astype(old['A'].dtype), old['A'], alpha)
                if 'G' not in res:
                    g_new = L.compute_g_factor(spec, captures[name]['g'],
                                               compute_dtype=cdt)
                    if extras is not None:
                        g_new = g_new + extras['G_a']
                    res['G'] = F.update_running_avg(
                        g_new.astype(old['G'].dtype), old['G'], alpha)
            out[name] = res
        return out

    @profiling.scope('kfac/factors')
    def update_factors(self, state: dict, captures: dict,
                       factor_decay=None) -> dict:
        """EWMA-update all factor running averages from captures.

        Reference: compute_factors + allreduce (preconditioner.py:566-575,
        525-533); under GSPMD the allreduce is implicit in the covariance
        contraction over the batch-sharded captures.
        """
        alpha = self.factor_decay if factor_decay is None else factor_decay
        if self.fused_contraction_active():
            return self._fused_blend_factors(state['factors'], captures,
                                             alpha)
        contribs = self.factor_contribs(captures)
        new_factors = {}
        for name in self.specs:
            old = state['factors'][name]
            a_new = contribs[name]['A'].astype(old['A'].dtype)
            g_new = contribs[name]['G'].astype(old['G'].dtype)
            new_factors[name] = {
                'A': F.update_running_avg(a_new, old['A'], alpha),
                'G': F.update_running_avg(g_new, old['G'], alpha)}
        return new_factors

    @profiling.scope('kfac/factors')
    def accumulate_factors(self, state: dict, captures: dict,
                           factor_decay=None) -> tuple[dict, jax.Array]:
        """Deferred-reduction factor step: fold one batch's contribution
        into the local accumulator, leave the running averages alone.

        ``acc ← α·acc + (1-α)·c`` and ``decay ← α·decay``; at the
        window boundary :meth:`reduce_factors` applies
        ``F ← decay·F + acc`` — by EMA linearity exactly the per-step
        recursion's value at the boundary (up to fp associativity).
        Returns ``(new_accum, new_decay)``. The accumulator fold is the
        same ``α·old + (1-α)·new`` blend as the eager EMA, so the r21
        fused kernel serves both.
        """
        alpha = self.factor_decay if factor_decay is None else factor_decay
        if self.fused_contraction_active():
            return (self._fused_blend_factors(state['factor_accum'],
                                              captures, alpha),
                    alpha * state['accum_decay'])
        contribs = self.factor_contribs(captures)
        acc = state['factor_accum']
        new_acc = {}
        for name in self.specs:
            old = acc[name]
            new_acc[name] = {
                which: F.update_running_avg(
                    contribs[name][which].astype(old[which].dtype),
                    old[which], alpha)
                for which in ('A', 'G')}
        return new_acc, alpha * state['accum_decay']

    @profiling.scope('kfac/factors')
    def reduce_factors(self, state: dict, acc: dict, decay) -> dict:
        """Deferred-reduction window boundary: apply the accumulated
        contributions to the running averages (single-chip form — no
        collective; the SPMD analogue pmeans ``acc`` first)."""
        new_factors = {}
        for name in self.specs:
            old = state['factors'][name]
            new_factors[name] = {
                which: (decay * old[which]
                        + acc[name][which]).astype(old[which].dtype)
                for which in ('A', 'G')}
        return new_factors

    def _bucketed_eigh(self, mats: dict[str, jax.Array],
                       prev: dict[str, jax.Array] | None = None
                       ) -> dict[str, tuple[jax.Array, jax.Array]]:
        """Eigendecompose a dict of SPD matrices, batching equal sizes.

        Equal-size factors are stacked and decomposed with one vmapped
        fp32 ``eigh`` — the TPU-native answer to the reference's per-layer
        sequential cuSOLVER calls (base.py:432-441), and the unit that
        ``parallel.distributed`` shards across the mesh. ``prev`` maps the
        same keys to the previous firing's eigenbases; when present (and
        ``eigh_method`` is 'auto'/'warm') the decomposition is the
        warm-start matmul-only polish instead of a cold eigh.
        """
        out: dict[str, tuple[jax.Array, jax.Array]] = {}
        method = resolve_eigh_method(self.eigh_method)
        for names, stack in _size_buckets(mats):
            q_prev = None
            if prev is not None and method == 'auto':
                q_prev = jnp.stack([prev[n].astype(jnp.float32)
                                    for n in names])
            qs, ds = linalg.batched_eigh(
                stack, method, clip=0.0, q_prev=q_prev,
                polish_iters=self.eigh_polish_iters)
            for i, n in enumerate(names):
                out[n] = (qs[i], ds[i])
        return out

    def _bucketed_lowrank(self, mats: dict[str, jax.Array],
                          prev: dict[str, jax.Array] | None = None
                          ) -> dict[str, tuple[jax.Array, jax.Array]]:
        """Truncated-eigendecompose a dict of SPD matrices, batching
        equal sizes (the r19 low-rank analogue of :meth:`_bucketed_eigh`).

        ``prev`` maps the same keys to the carried (dim, r) truncated
        bases; when present the decomposition is the subspace-refresh +
        warm polish, else the deterministic Gaussian range-finder
        sketch (cold rebuilds). Unlike the exact path, warm starting is
        not gated on ``eigh_method`` — the carried basis IS the
        low-rank state, re-randomizing it every firing would throw the
        converged subspace away.
        """
        out: dict[str, tuple[jax.Array, jax.Array]] = {}
        for names, stack in _size_buckets(mats):
            q_prev = (jnp.stack([prev[n].astype(jnp.float32)
                                 for n in names])
                      if prev is not None else None)
            qs, ds = linalg.batched_lowrank_eigh(
                stack, self.inv_lowrank_rank, q_prev=q_prev,
                polish_iters=self.eigh_polish_iters)
            for i, n in enumerate(names):
                out[n] = (qs[i], ds[i])
        return out

    def _bucketed_inverse(self, mats: dict[str, jax.Array], damping
                          ) -> dict[str, jax.Array]:
        """Damped-inverse a dict of SPD matrices, batching equal sizes.

        Non-eigen analogue of :meth:`_bucketed_eigh` (reference damped
        Cholesky inverse, kfac/layers/base.py:432-441): 'newton' runs the
        matmul-only Newton–Schulz stack (Pallas VMEM-resident on TPU),
        'cholesky' a vmapped XLA Cholesky inverse. Per-bucket method
        comes from :meth:`method_for_dim` (callers only route factors
        here whose dim resolves to a non-eigen method).
        """
        out: dict[str, jax.Array] = {}
        for names, stack in _size_buckets(mats):
            invs = pallas_kernels.damped_inverse_stack(
                stack, damping, self.method_for_dim(stack.shape[-1]),
                iters=self.newton_iters)
            for i, n in enumerate(names):
                out[n] = invs[i]
        return out

    @profiling.scope('kfac/inverses')
    def update_inverses(self, state: dict, damping, *,
                        warm: bool = True,
                        chunk: int | None = None) -> dict:
        """Recompute inverses/eigendecompositions from current factors.

        Reference: compute_inverses (preconditioner.py:555-564,
        base.py:198-308). Embedding A is diagonal: elementwise inverse
        (embedding.py fixed version). ``warm`` (default) seeds the eigen
        path from the previous bases in ``state['inverses']`` (the
        eigh_method='auto' fast path); pass ``warm=False`` where the
        stored bases are untrustworthy (e.g. rebuilding from a
        factor-only checkpoint, where inverse slots are fresh identity).

        ``chunk``: pipelined firing — recompute only the work items the
        :meth:`inverse_chunk_plan` assigns to this chunk index, passing
        every other slot through from ``state['inverses']`` unchanged.
        ``None`` (monolithic, the default) fires everything. Per-bucket
        decompositions are identical either way (chunking selects whole
        same-dim buckets, never splits one), which is what makes a
        frozen-factor pipelined window bit-identical to one monolithic
        firing (test-pinned).
        """
        plan = (self.inverse_chunk_plan(state['factors'])
                if self.pipelined_firing else None)
        if chunk is not None and plan is None:
            raise ValueError('inv_chunk requires inv_pipeline_chunks > 1 '
                             'or inv_staleness=1')

        def fires(key: tuple) -> bool:
            return chunk is None or plan[key] == chunk

        # Split the dense factors by per-dim method ('auto' mixes the
        # groups; global modes put everything in one). Prev-basis warm
        # starts apply to the eigen-family groups (exact + lowrank).
        eigen_mats: dict[str, jax.Array] = {}
        lowrank_mats: dict[str, jax.Array] = {}
        inv_mats: dict[str, jax.Array] = {}
        prev: dict[str, jax.Array] = {}
        sides: dict[str, tuple[str | None, str]] = {}
        for name, spec in self.specs.items():
            f = state['factors'][name]
            ma, mg = self._side_methods(spec, f['A'].shape[-1],
                                        f['G'].shape[-1])
            sides[name] = (ma, mg)
            if spec.kind == CONV2D_GROUPED:
                continue
            for which, m in (('A', ma), ('G', mg)):
                if m is None:
                    continue
                if not fires(('mat', name, which)):
                    continue
                key = f'{name}/{which}'
                if m == 'eigen':
                    eigen_mats[key] = f[which]
                    if warm:
                        prev[key] = state['inverses'][name][f'Q{which}']
                elif m == 'lowrank':
                    lowrank_mats[key] = f[which]
                    if warm:
                        prev[key] = state['inverses'][name][f'Q{which}']
                else:
                    inv_mats[key] = f[which]

        if plan is None:
            eigs = self._bucketed_eigh(eigen_mats, prev if warm else None)
            eigs.update(self._bucketed_lowrank(
                lowrank_mats, prev if warm else None))
            invs = self._bucketed_inverse(inv_mats, damping)
        else:
            # Pipelined mode (k > 1): decompose in the SAME per-chunk
            # sub-stacks whether this is a monolithic firing (all
            # groups) or one chunk's firing (its group alone). The
            # frozen-window bit-identity contract is then structural —
            # it does not rest on the backend's batched kernels being
            # slice-stable across batch sizes, which they are NOT
            # (observed on CPU: a 1-matrix vs 6-matrix vmapped polish
            # rotates Q by O(1) within near-degenerate eigenvalue
            # clusters; same amplification class as PERF.md's
            # static-vs-dynamic fusion note).
            def by_chunk(mats: dict) -> dict[int, dict]:
                out: dict[int, dict] = {}
                for key, m in mats.items():
                    name, which = key.rsplit('/', 1)
                    out.setdefault(plan[('mat', name, which)],
                                   {})[key] = m
                return out

            eigs, invs = {}, {}
            for _j, mats in sorted(by_chunk(eigen_mats).items()):
                eigs.update(self._bucketed_eigh(
                    mats, prev if warm else None))
            for _j, mats in sorted(by_chunk(lowrank_mats).items()):
                eigs.update(self._bucketed_lowrank(
                    mats, prev if warm else None))
            for _j, mats in sorted(by_chunk(inv_mats).items()):
                invs.update(self._bucketed_inverse(mats, damping))

        new_inv = {}
        for name, spec in self.specs.items():
            old = state['inverses'][name]
            if spec.kind == CONV2D_GROUPED:
                new_inv[name] = (grouped_block_inverses(
                    state['factors'][name], damping, self.inv_dtype)
                    if fires(('grouped', name)) else old)
                continue
            ma, mg = sides[name]
            # A dense layer with exactly one eigen-family side is
            # *mixed*: that side is additionally baked into a dense
            # damped inverse at THIS firing's damping (linalg.
            # eigen_side_inverse — truncated-aware, the low-rank bake
            # carries the I/λ tail complement), so both sides of the
            # split operator carry the same firing-time λ — the
            # reference non-eigen timing semantics — and precondition
            # does no per-step eigen-side reconstruction. Q/d stay
            # stored for the next firing's warm start. (Under chunked
            # firing the two sides may bake at different phase steps'
            # λ — the same situation a damping schedule already
            # creates across firings.)
            mixed = (spec.kind != EMBEDDING
                     and eigen_family(ma) != eigen_family(mg))
            # Chunked firing: start from the stored entry and overwrite
            # only the sides whose bucket fires this chunk.
            entry: dict[str, Any] = dict(old) if chunk is not None else {}
            if spec.kind == EMBEDDING:
                if fires(('diag', name)):
                    entry['A_inv'] = linalg.get_elementwise_inverse(
                        state['factors'][name]['A'].astype(jnp.float32),
                        damping=damping).astype(self.inv_dtype)
            elif eigen_family(ma):
                if fires(('mat', name, 'A')):
                    qa, da = eigs[f'{name}/A']
                    entry['QA'] = qa.astype(self.inv_dtype)
                    entry['dA'] = da.astype(self.inv_dtype)
                    if mixed:
                        entry['A_inv'] = linalg.eigen_side_inverse(
                            qa, da, damping).astype(self.inv_dtype)
            elif fires(('mat', name, 'A')):
                entry['A_inv'] = invs[f'{name}/A'].astype(self.inv_dtype)
            if eigen_family(mg):
                if fires(('mat', name, 'G')):
                    qg, dg = eigs[f'{name}/G']
                    entry['QG'] = qg.astype(self.inv_dtype)
                    entry['dG'] = dg.astype(self.inv_dtype)
                    if mixed:
                        entry['G_inv'] = linalg.eigen_side_inverse(
                            qg, dg, damping).astype(self.inv_dtype)
            elif fires(('mat', name, 'G')):
                entry['G_inv'] = invs[f'{name}/G'].astype(self.inv_dtype)
            new_inv[name] = entry
        return new_inv

    @profiling.scope('kfac/precond')
    def precondition(self, state: dict, grads: dict, damping, lr,
                     layer_filter: Sequence[str] | None = None,
                     with_stats: bool = False, gates: dict | None = None):
        """Precondition registered layers' grads; KL-clip scale on-device.

        Reference: compute_preconditioned_gradients + _compute_grad_scale +
        update_gradients (preconditioner.py:577-590,661-682). Unregistered
        params pass through unchanged. ``layer_filter`` restricts which
        layers this device computes (MEM/HYBRID placement).

        Dense layers are bucketed by gradient-matrix shape and
        preconditioned as ONE vmapped batched matmul per bucket — the
        single-chip analogue of the row-sharded KAISA batching
        (``parallel.distributed._rowsharded_precond_mats``). On a
        transformer, the q/k/v/o and MLP Denses of every block share
        shapes, so ~100 per-layer (dim, dim) matmul dispatches collapse
        into a handful of batched MXU kernels. Within a bucket the
        per-slice contraction is the same matmul the per-layer path ran
        (vmap adds a batch dim; it does not reassociate a slice's
        contraction) — default-dtype bit-identity with the historical
        per-layer dispatch is pinned on the CPU test backend
        (tests/test_mixed_precision.py); ``precond_bucketing=False``
        restores the per-layer loop exactly if a backend's batched
        kernel ever tiles differently.

        ``with_stats=True`` additionally returns
        ``(out, observability.metrics.precond_stats(...))`` — ν, grad /
        preconditioned-grad norms and per-shape-bucket norms, all traced
        scalars (the metrics path; default False is the historical
        single-value return).

        ``gates`` (r16 self-healing quarantine): an optional
        ``{shape-bucket key -> traced 0/1 scalar}`` dict (keys from
        ``observability.metrics.shape_key``, the same grouping the
        bucketed paths batch over). A gated-off (0) bucket's layers
        fall back to the RAW gradient direction — plain SGD — via
        ``jnp.where`` (a ``select``: NaN/Inf in the unselected
        preconditioned branch does not propagate), applied BEFORE the
        KL-clip so the clip scale and all downstream stats see the
        blended directions. Gate VALUES are traced scalars riding in
        ``hyper`` (engine), so flipping one is a value change — zero
        retraces. ``None`` (default) is the bit-identical historical
        path.
        """
        names = list(self.specs) if layer_filter is None else list(
            layer_filter)
        cdt = self.precond_compute_dtype
        grad_mats = {
            name: L.grads_to_matrix(self.specs[name],
                                    _get(grads, self.specs[name].path))
            for name in names}
        if self.precond_bucketing:
            precond_mats, fused_vg = self._bucketed_precond_mats(
                state['inverses'], grad_mats, damping, names)
        else:
            precond_mats, fused_vg = {}, {}
        for name in names:
            if name in precond_mats:
                continue  # dense layer: computed by a shape bucket
            spec = self.specs[name]
            inv = state['inverses'][name]
            # Per-layer path for the non-dense kinds: embedding A is the
            # diagonal elementwise inverse; grouped convs broadcast the
            # batched G_inv @ grad @ A_inv over their block stacks.
            # Same dispatch as the SPMD preconditioner:
            # linalg.precondition_dispatch.
            precond_mats[name] = linalg.precondition_dispatch(
                grad_mats[name], inv, damping,
                diag_a=(inv['A_inv'] if spec.kind == EMBEDDING else None),
                compute_dtype=cdt)

        if gates is not None:
            # Quarantine blend (r16): a gated-off bucket serves the raw
            # gradient. jnp.where is a select — the poisoned
            # preconditioned branch's NaNs stay un-propagated.
            for name in names:
                g = gates.get(obs_metrics.shape_key(
                    grad_mats[name].shape))
                if g is None:
                    continue
                pm = precond_mats[name]
                precond_mats[name] = jnp.where(
                    jnp.asarray(g, jnp.float32) >= 0.5, pm,
                    grad_mats[name].astype(pm.dtype))

        if self.kl_clip is not None:
            # Fused with the precondition pass: the grad matrices are
            # already live (no second grads_to_matrix walk), and XLA
            # fuses each product-reduce with its bucket's batched
            # matmul output. Accumulation stays per-layer in
            # registration order — the historical summation order, so
            # the clip scale is bit-stable against bucketing. An r21
            # fused bucket already reduced its per-slice v·g in the
            # kernel epilogue (no second full-tensor pass); the
            # per-layer scalars join the sum in the same registration
            # order. The r16 gate blend rewrites precond_mats AFTER the
            # buckets ran, so gated runs fall back to the full-tensor
            # reduction — the fused partial would be stale.
            vg_sum = jnp.zeros((), jnp.float32)
            for name in names:
                if gates is None and name in fused_vg:
                    vg_sum += fused_vg[name] * lr ** 2
                else:
                    vg_sum += jnp.sum(precond_mats[name] *
                                      grad_mats[name].astype(jnp.float32)
                                      * lr ** 2)
            nu = jnp.minimum(
                1.0, jnp.sqrt(self.kl_clip / (jnp.abs(vg_sum) + 1e-30)))
        else:
            nu = jnp.ones((), jnp.float32)

        stats = (obs_metrics.precond_stats(grad_mats, precond_mats, nu)
                 if with_stats else None)
        out = jax.tree.map(lambda x: x, grads)  # copy structure
        for name in names:
            spec = self.specs[name]
            sub = _get(grads, spec.path)
            new_sub = L.matrix_to_grads(
                spec, (nu * precond_mats[name]).astype(jnp.float32), sub)
            out = _set(out, spec.path, jax.tree.map(
                lambda n, o: n.astype(o.dtype), new_sub, sub))
        return (out, stats) if with_stats else out

    def _bucketed_precond_mats(self, inverses: dict, grad_mats: dict,
                               damping, names: Sequence[str]
                               ) -> tuple[dict, dict]:
        """Batched precondition matmuls for the dense layers in ``names``.

        Returns ``(mats, vg)``: ``mats`` maps each bucketed layer to its
        preconditioned matrix; ``vg`` maps the layers whose bucket ran
        the r21 fused kernel to the already-reduced KL-clip partial
        ``sum(v * g)`` (fp32, pre-``lr**2``) from the kernel epilogue —
        empty on the stock path. Layers are grouped by gradient-matrix
        shape; each group stacks its grads and inverse operands and runs
        ONE batched matmul chain — per-group entry keys are uniform
        because the per-dim method is a function of the factor dims
        alone (``method_for_dim``), so a shape group is wholly
        eigen-typed (QA/dA/QG/dG) or wholly baked (A_inv/G_inv; mixed
        layers carry baked inverses for both sides). Embedding
        (diagonal A) and grouped-conv (block-stack) layers are not
        dense (g, a) matmuls and stay on the caller's per-layer path.

        With ``fused_precondition`` engaged (and its probe green), a
        full-rank eigen or baked bucket within the Pallas dim budget
        runs :func:`pallas_kernels.fused_bucket_precondition` — the
        two-sided basis rotation, damped eigenvalue divide, and the
        KL-clip v·g reduction in one VMEM-resident kernel per bucket
        slice. r19 truncated low-rank buckets (rectangular QA/QG) keep
        the stock dispatch, as does everything when the knob is off.
        """
        cdt = self.precond_compute_dtype
        fused = self.fused_precond_active()
        interp = jax.default_backend() != 'tpu'
        groups: dict[tuple[int, ...], list[str]] = {}
        for name in names:
            if self.specs[name].kind in (EMBEDDING, CONV2D_GROUPED):
                continue
            groups.setdefault(tuple(grad_mats[name].shape),
                              []).append(name)
        mats: dict = {}
        vg: dict = {}
        for members in groups.values():
            gstack = jnp.stack([grad_mats[n] for n in members])
            e0 = inverses[members[0]]
            keys = (('A_inv', 'G_inv') if 'A_inv' in e0 or 'G_inv' in e0
                    else ('QA', 'dA', 'QG', 'dG'))
            entry = {k: jnp.stack([inverses[n][k] for n in members])
                     for k in keys}
            if fused and _fused_bucket_ok(entry):
                vs, vgs = pallas_kernels.fused_bucket_precondition(
                    gstack, entry, damping, compute_dtype=cdt,
                    interpret=interp)
                for i, n in enumerate(members):
                    mats[n] = vs[i]
                    vg[n] = vgs[i]
                continue
            vs = jax.vmap(
                lambda gm, e: linalg.precondition_dispatch(
                    gm, e, damping, compute_dtype=cdt))(gstack, entry)
            for i, n in enumerate(members):
                mats[n] = vs[i]
        return mats, vg

    # ------------------------------------------------------------------
    # The full step
    # ------------------------------------------------------------------

    def step(self, state: dict, grads: dict, captures: dict, *,
             damping=None, lr=None, factor_decay=None,
             factor_update_freq=None, inv_update_freq=None,
             factor_update: bool | None = None,
             inv_update: bool | None = None,
             inv_chunk: int | None = None,
             factor_reduce: bool = False,
             factor_snapshot: bool = False,
             gates: dict | None = None) -> tuple[dict, dict]:
        """One K-FAC update: returns (preconditioned_grads, new_state).

        The analogue of reference KFAC.step() (preconditioner.py:472-523).
        Cadence gating comes in two forms:

          - **Static** (recommended on TPU): pass Python bools
            ``factor_update`` / ``inv_update`` — the caller owns the
            schedule (``step % freq == 0`` on a host counter) and the
            gated work is simply present or absent from the traced
            program. Two program variants get compiled; the expensive
            decomposition program exists only where it runs.
          - **Dynamic** (``None``, the default): ``lax.cond`` on the
            on-device step counter, fully schedulable without
            recompilation. CAUTION: on TPU, a conditional whose branch
            holds the O(n^3) decompositions degrades the surrounding
            program — measured 10-18x step slowdowns on v5e from
            XLA layout/copy pathologies around the cond — so training
            loops should prefer the static form (the engine and
            ``DistributedKFAC.build_train_step`` do).

        ``inv_chunk``: pipelined inverse firing (static cadence only —
        a Python int, mutually exclusive with ``inv_update=True``):
        recompute only chunk ``j``'s share of the inverse work this
        step (see ``inv_pipeline_chunks`` / :meth:`update_inverses`).
        The engine fires chunk ``j`` on phase step
        ``j * inv_update_freq / k`` of each cadence window; each chunk
        value is its own statically-compiled program variant. The
        dynamic (``None``-flag) path always fires monolithically —
        chunking is a static-program-structure feature by design
        (PERF.md pitfall 2).

        ``factor_reduce`` (requires ``deferred_factor_reduction``,
        static): apply the locally-accumulated factor contributions to
        the running averages this step — the single collective per
        window on the SPMD path. ``factor_snapshot`` (requires
        ``inv_staleness=1``, static): refresh ``frozen_factors`` from
        this step's post-update factors (window-head steps); in-window
        chunk firings always decompose the carried snapshot, and a
        monolithic ``inv_update=True`` firing snapshots-then-fires
        (eager semantics — the step-0 warmup). Both features are
        static-cadence only: dynamic (``None``) flags raise.

        ``gates``: per-shape-bucket quarantine mask (r16 self-healing)
        — see :meth:`precondition`. Traced scalar VALUES; ``None``
        (default) keeps the historical program bit-identical.
        """
        damping = self.damping if damping is None else damping
        lr = self.lr if lr is None else lr
        f_freq = (self.factor_update_freq if factor_update_freq is None
                  else factor_update_freq)
        i_freq = (self.inv_update_freq if inv_update_freq is None
                  else inv_update_freq)
        step = state['step']

        track = self.collect_metrics or self.nonfinite_guard
        if self.hierarchical_reduce:
            raise ValueError(
                'hierarchical_reduce is SPMD-only (it reduces over '
                "mesh slice axes) — use DistributedKFAC on a "
                'multislice.make_multislice_mesh mesh with '
                'num_slices > 1')
        if self.deferred_factor_reduction:
            # Deferred reduce: the EWMA (and, under SPMD, the factor
            # collective) advances only on factor_reduce steps; factor
            # steps fold into the local accumulator. Static cadence
            # only — the boundary update is program structure.
            if factor_update is None:
                raise ValueError(
                    'deferred_factor_reduction requires static cadence '
                    'flags (Python-bool factor_update/factor_reduce) — '
                    'the window-boundary reduce is static program '
                    'structure, like inv_chunk')
            acc, decay = state['factor_accum'], state['accum_decay']
            if factor_update:
                acc, decay = self.accumulate_factors(state, captures,
                                                     factor_decay)
            if factor_reduce:
                candidate = self.reduce_factors(state, acc, decay)
                # Guard/metrics check the post-accumulation candidate
                # at the reduce point (the collective-safe analogue of
                # the eager per-step check); a non-finite window is
                # skipped WHOLE and the accumulator resets either way.
                factors, finite_f = guard_nonfinite_factors(
                    candidate, state['factors'], self.nonfinite_guard)
                acc = jax.tree.map(jnp.zeros_like, acc)
                decay = jnp.ones((), jnp.float32)
            else:
                factors = state['factors']
                finite_f = jnp.ones((), jnp.int32)
            state_f = {**state, 'factors': factors,
                       'factor_accum': acc, 'accum_decay': decay}
        else:
            if factor_reduce:
                raise ValueError(
                    'factor_reduce requires '
                    'deferred_factor_reduction=True')
            if track:
                # Tracked form: the factor branch additionally yields
                # the candidate factors' finiteness flag
                # (guard + metrics).
                factors, finite_f = cadence_gate(
                    factor_update, step, f_freq,
                    lambda: self._tracked_factor_update(state, captures,
                                                        factor_decay),
                    lambda: (state['factors'], jnp.ones((), jnp.int32)))
            else:
                # Metrics/guard off: the historical program, untouched
                # (bit-identity pinned by tests/test_observability.py).
                factors = cadence_gate(
                    factor_update, step, f_freq,
                    lambda: self.update_factors(state, captures,
                                                factor_decay),
                    lambda: state['factors'])
            state_f = {**state, 'factors': factors}

        if self.inv_staleness:
            if inv_update is None:
                raise ValueError(
                    'inv_staleness=1 requires static cadence flags '
                    '(the frozen-snapshot firing schedule is static '
                    'program structure, like inv_chunk)')
            # Window-head steps (and a monolithic firing — the step-0
            # warmup, which must decompose the step's fresh factors,
            # not the identity seeds) refresh the snapshot; everything
            # else decomposes the carried one.
            frozen = (state_f['factors']
                      if factor_snapshot or inv_update
                      else state['frozen_factors'])
            state_f = {**state_f, 'frozen_factors': frozen}
            fire_state = {**state_f, 'factors': frozen}
        else:
            if factor_snapshot:
                raise ValueError(
                    'factor_snapshot requires inv_staleness=1')
            fire_state = state_f

        if inv_chunk is not None:
            k = self.inv_pipeline_chunks
            if inv_update:
                raise ValueError(
                    'inv_chunk is mutually exclusive with '
                    'inv_update=True (a monolithic firing already '
                    'covers every chunk)')
            if not 0 <= inv_chunk < k:
                raise ValueError(
                    f'{inv_chunk=} out of range for '
                    f'inv_pipeline_chunks={k}')
            with profiling.annotate(f'kfac/inverse/chunk{inv_chunk}'):
                inverses = self.update_inverses(fire_state, damping,
                                                chunk=inv_chunk)
            chunk_phase = jnp.asarray((inv_chunk + 1) % k, jnp.int32)
        else:
            inverses = cadence_gate(
                inv_update, step, i_freq,
                lambda: self.update_inverses(fire_state, damping),
                lambda: state['inverses'])
            # Static monolithic firing resets the pipeline position;
            # otherwise (no firing, or the dynamic cond path — which
            # only ever fires monolithically from phase 0) the stored
            # phase passes through untouched.
            chunk_phase = (jnp.zeros((), jnp.int32) if inv_update
                           else state['inv_chunk_phase'])
        state_i = {**state_f, 'inverses': inverses,
                   'inv_chunk_phase': chunk_phase}

        if not self.collect_metrics:
            precond = self.precondition(state_i, grads, damping, lr,
                                        gates=gates)
            new_state = {**state_i, 'step': step + 1}
            return precond, new_state

        precond, stats = self.precondition(state_i, grads, damping, lr,
                                           with_stats=True, gates=gates)
        one = lambda: jnp.ones((), jnp.int32)
        zero = lambda: jnp.zeros((), jnp.int32)
        did_f = cadence_gate(factor_update, step, f_freq, one, zero)
        did_i = (zero() if inv_chunk is not None
                 else cadence_gate(inv_update, step, i_freq, one, zero))
        did_c = one() if inv_chunk is not None else zero()
        new_state = {**state_i, 'step': step + 1,
                     'metrics': obs_metrics.update_metrics(
                         state['metrics'], damping=damping, stats=stats,
                         did_factor=did_f, did_inv=did_i,
                         did_chunk=did_c,
                         factor_finite=finite_f,
                         eig_clipped=obs_metrics.count_clipped_eigvals(
                             inverses))}
        return precond, new_state

    # ------------------------------------------------------------------
    # Introspection / checkpoint helpers
    # ------------------------------------------------------------------

    def memory_usage(self, state: dict) -> dict[str, int]:
        """Bytes held by each K-FAC state component.

        Reference: KFAC.memory_usage (preconditioner.py:592-614); capture
        buffers don't persist here (they are step-local values).
        """
        return {'factors': _tree_size_bytes(state['factors']),
                'inverses': _tree_size_bytes(state['inverses'])}

    def state_dict(self, state: dict, include_inverses: bool = False):
        """Checkpointable pytree: factors + step, inverses optional.

        Inverses are recomputed on load rather than stored, matching the
        reference's checkpoint policy (preconditioner.py:294-353,
        README.md:222-223).
        """
        out = {'step': state['step'], 'factors': state['factors'],
               'inv_chunk_phase': state.get(
                   'inv_chunk_phase', jnp.zeros((), jnp.int32))}
        # r14 overlap state: present only when the knobs are on, so
        # default checkpoints keep the historical layout (MIGRATION.md).
        for key in ('factor_accum', 'accum_decay', 'frozen_factors'):
            if key in state:
                out[key] = state[key]
        if include_inverses:
            out['inverses'] = state['inverses']
        return out

    def load_state_dict(self, sd: dict, params,
                        compute_inverses: bool = True) -> dict:
        """Rebuild full K-FAC state from a checkpointed pytree.

        Validates layer congruence like reference load_state_dict
        (preconditioner.py:334-336) and recomputes inverses from factors.
        """
        state = self.init_state(params)
        if set(sd['factors']) != set(state['factors']):
            raise ValueError(
                'checkpoint layers do not match registered layers: '
                f'{sorted(sd["factors"])} vs {sorted(state["factors"])}')
        state = {**state, 'step': jnp.asarray(sd['step'], jnp.int32),
                 'factors': sd['factors'],
                 # Pre-r9 checkpoints have no pipeline position: default
                 # 0 (window head — always a safe resume point, the
                 # engine re-derives the schedule from the step counter).
                 'inv_chunk_phase': jnp.asarray(
                     sd.get('inv_chunk_phase', 0), jnp.int32)}
        state = _overlay_overlap_state(state, sd)
        # A checkpoint written under a different inverse layout (e.g.
        # 'eigen' saved, 'auto' loading) is structurally incompatible —
        # rebuild from factors instead of splicing mismatched slots in.
        # Shapes matter as much as key sets (r19): a pre-r19 full-rank
        # (d, d) basis shares the QA/dA key names with a truncated
        # (d, r) one — splicing it into a low-rank config (or vice
        # versa) would hand the wrong-shape operand to every firing.
        import numpy as np
        compatible = 'inverses' in sd and all(
            set(sd['inverses'].get(n, ())) == set(state['inverses'][n])
            and all(tuple(np.shape(sd['inverses'][n][k]))
                    == tuple(np.shape(state['inverses'][n][k]))
                    for k in state['inverses'][n])
            for n in state['inverses'])
        if compatible and not _degenerate_bases(sd['inverses']):
            state = {**state, 'inverses': sd['inverses']}
        elif compute_inverses:
            # warm=False: the fresh state's identity bases are not a
            # valid warm start for arbitrary checkpointed factors — use
            # an exact decomposition for this one-time host-side rebuild.
            state = {**state,
                     'inverses': self.update_inverses(state, self.damping,
                                                      warm=False)}
        return state


def _overlay_overlap_state(state: dict, sd: dict) -> dict:
    """Restore the r14 compute/communication-overlap state fields.

    ``factor_accum``/``accum_decay`` (deferred factor reduction) and
    ``frozen_factors`` (inv_staleness=1) are overlaid from the
    checkpoint when the live config carries them AND the saved shapes
    match; otherwise the init seeds stand — pre-r14 bundles (and
    cross-topology elastic restores, whose per-device accumulator
    stacks cannot transfer) resume as "eager reduce / snapshot =
    restored factors": at most one window of un-reduced statistics is
    dropped, and the snapshot seeds from the factors the checkpoint
    DID reduce (never the identity). The accumulator and its decay
    product move together — splicing one without the other would
    decay the factors without the compensating contributions
    (MIGRATION.md). Single point of truth for the single-chip and
    SPMD loaders.
    """
    import numpy as np
    out = dict(state)
    if 'frozen_factors' in state:
        frozen = sd.get('frozen_factors')
        compatible = frozen is not None and jax.tree.structure(
            frozen) == jax.tree.structure(state['frozen_factors'])
        out['frozen_factors'] = (frozen if compatible
                                 else jax.tree.map(lambda x: x,
                                                   out['factors']))
    if 'factor_accum' in state:
        acc = sd.get('factor_accum')
        compatible = (
            acc is not None and 'accum_decay' in sd
            and jax.tree.structure(acc) == jax.tree.structure(
                state['factor_accum'])
            and all(tuple(np.shape(a)) == tuple(np.shape(b))
                    for a, b in zip(jax.tree.leaves(acc),
                                    jax.tree.leaves(
                                        state['factor_accum']))))
        if compatible:
            out['factor_accum'] = acc
            out['accum_decay'] = jnp.asarray(sd['accum_decay'],
                                             jnp.float32)
    return out


def guard_nonfinite_factors(new_factors: dict, old_factors: dict,
                            guard: bool) -> tuple[dict, jax.Array]:
    """``(factors, finite 0/1)`` — the non-finite factor-guard
    transition, single point of truth for the single-chip and SPMD
    steps (they must not drift).

    Finiteness is checked on the *candidate* post-average factors —
    collective-safe under SPMD (every device sees the same averaged
    values, so the skip cannot diverge across the mesh) and it catches
    NaN *and* Inf contamination from any capture batch. With ``guard``
    a non-finite candidate keeps the previous factors (reference
    GradScaler spirit, engine.py:75-80, extended to the factor
    statistics the reference leaves unprotected); without, the flag is
    detection-only (the metrics path).
    """
    finite = fp16_ops.tree_all_finite(new_factors)
    if guard:
        new_factors = jax.tree.map(
            lambda n, o: jnp.where(finite, n, o),
            new_factors, old_factors)
    return new_factors, finite.astype(jnp.int32)


def grouped_block_inverses(factors: dict, damping, inv_dtype) -> dict:
    """Per-group damped block inverses for a grouped-conv layer.

    One batched damped Cholesky per side over the ``(G, d, d)`` factor
    stacks (blocks are tiny — e.g. ``kh*kw+1`` per depthwise group, so
    eigen warm-start bookkeeping would cost more than it saves). Single
    point of truth for the single-chip and SPMD inverse updates.
    """
    return {'A_inv': pallas_kernels.damped_inverse_stack(
                factors['A'].astype(jnp.float32), damping,
                'cholesky').astype(inv_dtype),
            'G_inv': pallas_kernels.damped_inverse_stack(
                factors['G'].astype(jnp.float32), damping,
                'cholesky').astype(inv_dtype)}


def measured_unit_scale(measured: dict, dim_counts: dict[int, int],
                        scope: str) -> float:
    """Fit the ms-per-dim^3 factor for a measured chunk-cost dict.

    ``measured`` maps dim -> whole-bucket ms, ``dim_counts`` maps
    dim -> work units in that bucket (per-matrix counts on the
    single-chip planner, slots_per_col on the SPMD one). Measured ms
    and the dim^3 proxy are DIFFERENT UNITS, so a measurement must
    cover every dim in ``dim_counts`` (raises otherwise — a partial
    dict like ``{4096: 531.8}`` would weight the genuinely heaviest
    bucket ~1e7x too cheap and silently un-balance the plan). Returns
    the factor that converts remaining proxy costs (grouped/diagonal
    items) into the measured unit; 1.0 when nothing is measured.
    Shared by both planners so the unit discipline cannot drift.
    """
    if not measured:
        return 1.0
    from distributed_kfac_pytorch_tpu.ops.linalg import (
        decomposition_cost,
    )
    missing = sorted(d for d in dim_counts if d not in measured)
    if missing:
        raise ValueError(
            f'inv_pipeline_costs must cover every {scope} (missing '
            f'{missing}): measured ms and the dim^3 proxy are '
            'different units and cannot be mixed in one chunk packing '
            '— pass the full bucket_parts of a firing leg')
    proxy_total = sum(decomposition_cost(d, c)
                      for d, c in dim_counts.items())
    ms_total = sum(float(measured[d]) for d in dim_counts)
    return ms_total / proxy_total if ms_total > 0 else 1.0


def plan_inverse_chunks(items: Sequence[tuple[Any, float]],
                        k: int) -> dict[Any, int]:
    """Greedy LPT assignment of inverse work items onto ``k`` chunks.

    ``items`` are ``(key, cost)`` pairs (see
    :meth:`KFAC.inverse_chunk_items`); returns ``{key: chunk_index}``.
    Single point of truth for the single-chip and SPMD pipelined-firing
    paths — both must fire the same buckets on the same phase steps.
    Balance quality on the flagship factor sets is test-pinned
    (tests/test_inv_pipeline.py: max chunk load <= 1.5x the ideal
    ``total/k`` on the ResNet-50 and xl-LM sets).
    """
    from distributed_kfac_pytorch_tpu.parallel.placement import (
        load_balance,
    )
    assignment = load_balance(k, [cost for _, cost in items])
    return {key: chunk for (key, _), chunk in zip(items, assignment)}


def eigen_family(method: str | None) -> bool:
    """True for methods whose inverse representation is an eigenpair
    (Q, d) consumed through the eigen precondition path: the exact
    'eigen' dispatch and the r19 'lowrank' truncated one. Single point
    of truth for the mixed-layer logic in the single-chip and SPMD
    paths — a layer is *mixed* exactly when one side is eigen-family
    and the other is a baked dense inverse."""
    return method in ('eigen', 'lowrank')


def resolve_eigh_method(method: str) -> str:
    """Normalize the eigh-method alias: 'warm' behaves as 'auto'.

    Both polish when a previous basis exists and fall back to the exact
    eigh when not (one-time host-side rebuilds like load_state_dict).
    Single point of truth for the single-chip and SPMD dispatchers.
    """
    return 'auto' if method in ('auto', 'warm') else method


def q_stack_degenerate(q) -> bool:
    """True if a stored eigenbasis (or stack of bases) is unusable.

    Checkpoints written by pre-warm-eigh versions initialized inverse
    slots to zeros; Q=0 is a *fixed point* of the warm polish (every
    update is right-multiplication by Q), which would silently zero the
    preconditioned gradients forever. An orthonormal (n, n) basis has
    ``|Q|_F = sqrt(n)`` (a (B, n, n) stack: ``sqrt(B * n)``), so a tiny
    Frobenius norm is an unambiguous degeneracy signal. A TRUNCATED
    (n, r) basis (r19) has ``|Q|_F = sqrt(r)`` — the expectation counts
    columns, not rows, so deep truncations are not falsely flagged.

    Multi-host safe: on a sharded ``jax.Array`` only the *addressable*
    shards are inspected (fetching the global value of an array spanning
    other hosts' devices is impossible); an all-zero stack is all-zero
    in every shard. Host-side, eager — used only on checkpoint restore.
    """
    import numpy as np

    def shard_bad(arr) -> bool:
        a = np.asarray(arr)
        # Orthonormal COLUMNS: norm = sqrt(batch dims x column count).
        expect = np.sqrt(float(np.prod(a.shape[:-2], dtype=np.float64)
                               * a.shape[-1]))
        return float(np.linalg.norm(a)) < 0.5 * expect

    shards = getattr(q, 'addressable_shards', None)
    if shards is not None:
        return any(shard_bad(s.data) for s in shards)
    return shard_bad(q)


def _degenerate_bases(inverses: dict) -> bool:
    """True if any stored eigenbasis in a per-layer inverse dict is
    unusable (see :func:`q_stack_degenerate`); the caller falls back to
    recomputing inverses from factors (the reference's behavior,
    preconditioner.py:347-353). Checks whatever eigen slots exist —
    under 'auto' dispatch only the below-cutoff sides carry bases."""
    return any(q_stack_degenerate(entry[key])
               for entry in inverses.values()
               for key in ('QA', 'QG') if key in entry)


def _size_buckets(mats: dict[str, jax.Array]):
    """Group a dict of square matrices by size: yields (names, fp32 stack).

    Ordering is deterministic (dict insertion order within a size), so the
    stacked layout is stable across traces.
    """
    buckets: dict[int, list[str]] = {}
    for name, m in mats.items():
        buckets.setdefault(m.shape[-1], []).append(name)
    for dim, names in buckets.items():
        yield names, jnp.stack([mats[n].astype(jnp.float32)
                                for n in names])


def _get(tree, path: tuple[str, ...]):
    for part in path:
        tree = tree[part]
    return tree


def _set(tree, path: tuple[str, ...], value):
    """Immutable deep-set on nested dicts."""
    if not path:
        return value
    out = dict(tree)
    out[path[0]] = _set(tree[path[0]], path[1:], value)
    return out
