"""Tracing / timing utilities (reference kfac/utils.py:8-56).

The wall-clock trace table moved to
``observability.tracing`` (the r7 observability subsystem); the
``trace`` / ``get_trace`` / ``print_trace`` / ``clear_trace`` names
stay importable from here so reference-parity callers and existing
tests keep working unchanged.
"""

from __future__ import annotations

from typing import Any

import jax

# Re-exports (same objects — the module-level table is shared, so
# decorating through either path feeds one table).
from distributed_kfac_pytorch_tpu.observability.tracing import (  # noqa: F401
    _FUNC_TRACES,
    clear_trace,
    get_trace,
    print_trace,
    trace,
)


def tree_bytes(tree: Any) -> int:
    """Total bytes of all array leaves (for memory accounting)."""
    return sum(x.size * x.dtype.itemsize
               for x in jax.tree.leaves(tree) if hasattr(x, 'size'))


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Enable JAX's persistent compilation cache (measured: a repeat
    process compiles an identical program in ~0.01 s vs the full
    compile — on the tunneled dev chip that is minutes per flagship
    program). No reference analogue (torch eager has no compile step);
    this is TPU operational tooling.

    ``cache_dir`` defaults to ``$KFAC_COMPILE_CACHE`` or
    ``<package parent>/.jax_cache`` (the repo root when run from a
    checkout). Set ``KFAC_COMPILE_CACHE=0`` to disable (e.g. when
    measuring cold-compile behavior itself). Returns the cache dir in
    effect, or None when disabled/unavailable. Safe for timing benches:
    the cache affects compile time only, never the compiled program's
    execution.

    Deference rules: a cache dir already configured through JAX's own
    knobs (``JAX_COMPILATION_CACHE_DIR`` or a prior ``jax.config``
    update) wins — this helper then changes nothing and returns the
    existing dir. An unwritable default location (e.g. an installed
    package under a read-only site-packages) disables the cache
    instead of crashing the entry script. This is deliberately a
    per-entry-point call, NOT a library import side effect: the
    library must never mutate global JAX config just by being
    imported.

    Known issue (observed on jax 0.8 in this tree): WARM cache reads
    segfault on the multi-device CPU backend — the second full test
    suite run crashes at trace time inside a shard_map trace, while
    cold runs and all on-chip warm paths (CLIs, bench legs) are clean.
    When the process *explicitly* names a multi-device CPU backend
    (``jax_platforms`` starts with cpu) the DEFAULT path refuses and
    actively disables, env var included. When the configuration is only
    *implicit* (``jax_platforms`` unset but multi-device CPU knobs set —
    the process may still resolve to an accelerator), the default path
    refuses to enable anything itself but leaves the user's own
    ``JAX_COMPILATION_CACHE_DIR`` untouched: destroying it in a process
    that resolves to TPU would be wrong (ADVICE r4), at the cost of
    residual segfault exposure if that process really is CPU-only AND
    the user exported the env var themselves. An explicit ``cache_dir``
    argument bypasses the guard (caller takes responsibility — that is
    what the unit tests use). ``KFAC_COMPILE_CACHE=0`` disables
    everywhere.
    """
    import os

    env = os.environ.get('KFAC_COMPILE_CACHE')
    if env is not None and env.strip().lower() in (
            '0', 'false', 'off', 'no', ''):
        return None
    if env is not None and env.strip().lower() in ('1', 'true', 'on', 'yes'):
        # Boolean-looking "enable" spellings mean "use the default dir",
        # not "use a relative directory literally named '1'".
        env = None
    if cache_dir is None:
        cpu_config = _multi_device_cpu_configured()
        if cpu_config == 'explicit':
            disable_compilation_cache()
            return None
        if cpu_config == 'implicit':
            # jax_platforms is unset; XLA_FLAGS merely *allows* a
            # multi-device CPU backend but the process may still resolve
            # to an accelerator. Don't enable (the CPU case segfaults on
            # warm reads) but don't destroy the user's own
            # JAX_COMPILATION_CACHE_DIR either.
            return None
    existing = jax.config.jax_compilation_cache_dir
    if os.environ.get('JAX_COMPILATION_CACHE_DIR'):
        return os.environ['JAX_COMPILATION_CACHE_DIR']
    if cache_dir is None and existing:
        return existing
    if cache_dir is None:
        cache_dir = env or os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            '.jax_cache')
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        return None
    jax.config.update('jax_compilation_cache_dir', cache_dir)
    # JAX's default min-compile-time threshold (~1 s) stays: it caches
    # exactly the expensive programs (flagship legs, train steps, big
    # test programs) while skipping the thousands of tiny helper jits.
    # An earlier min_compile_time=0.0 override was reverted after a
    # reproducible segfault in warm full-suite runs (trace-time crash
    # reading the cache; tiny-entry churn from overlapping processes is
    # the prime suspect) — the big programs are where the minutes are
    # anyway.
    return cache_dir


def disable_compilation_cache() -> None:
    """Turn the persistent compilation cache off for this process —
    including a cache inherited through JAX's own
    ``JAX_COMPILATION_CACHE_DIR`` env var. The single point of truth
    for the multi-device-CPU segfault workaround (see
    :func:`enable_compilation_cache`); used by the CPU-mesh test
    harness and the multichip dryrun.
    """
    import os

    os.environ.pop('JAX_COMPILATION_CACHE_DIR', None)
    jax.config.update('jax_compilation_cache_dir', None)


def raise_cpu_collective_timeouts(terminate_s: int = 600,
                                  warn_s: int = 120) -> None:
    """Raise XLA-CPU collective rendezvous timeouts via XLA_FLAGS.

    The virtual multi-device CPU mesh runs one thread per device on
    however few cores the host has; under compile load a device thread
    can be starved past XLA's default 40 s rendezvous termination
    timeout, which kills the process with a Fatal check ("Expected N
    threads to join the rendezvous...") — observed on the 1-core CI
    host between epoch-boundary program variants. Must run BEFORE the
    CPU backend initializes (XLA_FLAGS is read at backend init);
    existing user-provided values for these flags win.

    No-op on old jaxlib (< 0.5): the flags do not exist there, and XLA
    aborts the whole process on unknown ``XLA_FLAGS`` entries (fatal
    check in parse_flags_from_env.cc) — strictly worse than the starved
    rendezvous this guards against.
    """
    import os

    from distributed_kfac_pytorch_tpu import compat

    if not compat.cpu_collective_timeout_flags_supported():
        return
    flags = os.environ.get('XLA_FLAGS', '')
    add = []
    if '--xla_cpu_collective_call_terminate_timeout_seconds' not in flags:
        add.append('--xla_cpu_collective_call_terminate_timeout_seconds'
                   f'={terminate_s}')
    if '--xla_cpu_collective_call_warn_stuck_timeout_seconds' not in flags:
        add.append('--xla_cpu_collective_call_warn_stuck_timeout_seconds'
                   f'={warn_s}')
    if add:
        os.environ['XLA_FLAGS'] = (flags + ' ' + ' '.join(add)).strip()


def _multi_device_cpu_configured() -> str | None:
    """How this process is set up for a multi-device CPU backend (the
    configuration whose warm cache reads segfault) — decided from
    config/env only, WITHOUT initializing the backend (entry points
    still need jax.config.update('jax_platforms', ...) to work after
    this check).

    Returns ``'explicit'`` when ``jax_platforms`` names cpu first with
    multiple devices configured, ``'implicit'`` when ``jax_platforms``
    is unset but ``XLA_FLAGS`` forces >1 host-platform devices (the
    process may still resolve to an accelerator backend), and ``None``
    otherwise.
    """
    import os
    import re

    plats = jax.config.jax_platforms
    first = plats.split(',')[0] if plats else None
    m = re.search(r'xla_force_host_platform_device_count=(\d+)',
                  os.environ.get('XLA_FLAGS', ''))
    from distributed_kfac_pytorch_tpu import compat

    forced = bool(m and int(m.group(1)) > 1) or (
        compat.configured_cpu_device_count() > 1)
    if first == 'cpu' and forced:
        return 'explicit'
    if forced and first is None:
        return 'implicit'
    return None
