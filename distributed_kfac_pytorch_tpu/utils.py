"""Tracing / timing utilities (reference kfac/utils.py:8-56).

Decorator-based wall-clock tracing for host-side phases and dispatched
device work. ``sync=True`` calls ``jax.block_until_ready`` on the result
(the XLA analogue of the reference's pre/post ``backend.barrier()`` —
without it, timings measure async dispatch only).

Reference bugs fixed (SURVEY.md §8): ``clear_trace`` actually clears
(utils.py:11-12 rebinds a local) and ``get_trace`` has no undefined
variable (utils.py:18-19 ``max_times``).
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable

import jax

_FUNC_TRACES: dict[str, list[float]] = {}


def trace(sync: bool = False, name: str | None = None) -> Callable:
    """Decorator appending each call's duration to the module trace table.

    Args:
      sync: block on the result (and on a dummy device sync before
        starting) so the measurement covers device execution, not just
        dispatch.
      name: trace key (defaults to the function's __name__).
    """
    def decorator(fn):
        key = name or fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if sync:
                jax.block_until_ready(
                    [a for a in args if isinstance(a, jax.Array)])
            start = time.perf_counter()
            out = fn(*args, **kwargs)
            if sync:
                jax.block_until_ready(out)
            _FUNC_TRACES.setdefault(key, []).append(
                time.perf_counter() - start)
            return out

        return wrapper

    return decorator


def get_trace(average: bool = True, max_history: int | None = None
              ) -> dict[str, float]:
    """Per-key mean (or total) duration in seconds.

    ``max_history`` restricts to the most recent N samples.
    """
    out = {}
    for key, times in _FUNC_TRACES.items():
        window = times[-max_history:] if max_history else times
        if not window:
            continue
        out[key] = (sum(window) / len(window)) if average else sum(window)
    return out


def print_trace(average: bool = True, max_history: int | None = None
                ) -> None:
    for key, val in sorted(get_trace(average, max_history).items()):
        print(f'{key}: {val * 1000:.3f} ms')


def clear_trace() -> None:
    _FUNC_TRACES.clear()


def tree_bytes(tree: Any) -> int:
    """Total bytes of all array leaves (for memory accounting)."""
    return sum(x.size * x.dtype.itemsize
               for x in jax.tree.leaves(tree) if hasattr(x, 'size'))
