"""Native (C++) host-runtime components, loaded via ctypes.

The TPU compute path is JAX/XLA/Pallas; the host-side runtime work around
it — here the input-pipeline augmentation that the reference delegates to
torchvision's C transforms (examples/cnn_utils/datasets.py:14-17) — is
native C++ (csrc/), compiled on first use with the local toolchain and
bound through ctypes (no build-time dependency). Every native entry point
has a pure-numpy fallback with identical semantics, used when no C++
toolchain is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_CSRC = os.path.join(os.path.dirname(__file__), 'csrc')
_LIB_PATH = os.path.join(_CSRC, 'libkfac_native.so')
_lock = threading.Lock()
_lib = None
_lib_failed = False


def _build() -> bool:
    src = os.path.join(_CSRC, 'augment.cpp')
    # Compile to a per-process temp file and rename atomically: concurrent
    # first-use builds (every rank of a multi-host job on a shared
    # filesystem) then each produce a complete library, and dlopen never
    # sees a partially-written file.
    tmp = f'{_LIB_PATH}.{os.getpid()}.tmp'
    cmd = ['g++', '-O3', '-shared', '-fPIC', '-std=c++17', '-pthread',
           src, '-o', tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB_PATH)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def get_lib():
    """The loaded native library, or None (build failure is sticky)."""
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        if not os.path.exists(_LIB_PATH) or (
                os.path.getmtime(_LIB_PATH)
                < os.path.getmtime(os.path.join(_CSRC, 'augment.cpp'))):
            if not _build():
                _lib_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            lib.augment_batch.argtypes = [
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_int, ctypes.c_int]
            lib.augment_batch.restype = None
            _lib = lib
        except OSError:
            _lib_failed = True
        return _lib


def augment_batch(x: np.ndarray, ys: np.ndarray, xs: np.ndarray,
                  flip: np.ndarray, pad: int = 4,
                  n_threads: int | None = None) -> np.ndarray | None:
    """Reflect-pad + crop + flip a float32 NHWC batch natively.

    ``ys``/``xs`` are crop offsets into the padded image (in [0, 2*pad]),
    ``flip`` a 0/1 byte per image — the caller draws them (numpy RNG), so
    native and fallback paths are bit-identical. Returns None when the
    native library is unavailable (caller falls back to numpy).
    """
    lib = get_lib()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, np.float32)
    n, h, w, c = x.shape
    out = np.empty_like(x)
    ys = np.ascontiguousarray(ys, np.int32)
    xs = np.ascontiguousarray(xs, np.int32)
    flip = np.ascontiguousarray(flip, np.uint8)
    if n_threads is None:
        n_threads = min(8, os.cpu_count() or 1)
    fptr = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    lib.augment_batch(
        fptr(x), fptr(out), n, h, w, c,
        ys.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        xs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        flip.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        pad, n_threads)
    return out
