"""fp16 robustness: non-finite capture filtering + dynamic loss scaling.

Reference parity for the GradScaler integration
(kfac/layers/base.py:374-407 and kfac/preconditioner.py:12-16): the
reference unscales grad-output captures by the live GradScaler scale and
*drops* inf/NaN tensors at hook time with a warning; its training loop
rides ``torch.cuda.amp.GradScaler``'s dynamic scale. TPU bf16 needs none
of this (no loss scaling required — the default path), so everything
here is opt-in for true-fp16 runs.

jit-friendly redesign of both pieces:

  - dropping a tensor is a dynamic shape — the SPMD equivalent is
    *zeroing* it (:func:`sanitize_captures`): a zeroed call contributes
    nothing to the factor covariance sum, which is exactly what the
    reference's drop does to the accumulated average (the next EWMA
    update then averages over slightly fewer effective samples). The
    number of zeroed tensors is returned as an on-device count for the
    caller's metrics (a Python-side warning inside jit is impossible;
    the count is the observable).
  - GradScaler's schedule becomes a pure state transition
    (:func:`init_loss_scale` / :func:`update_loss_scale`): halve on any
    non-finite gradient and skip the step, double after
    ``growth_interval`` consecutive finite steps — the standard AMP
    policy, as a pytree usable inside one jitted train step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _tensor_finite(x) -> jax.Array:
    return jnp.isfinite(x.astype(jnp.float32)).all()


def tree_all_finite(tree) -> jax.Array:
    """Scalar bool: every element of every leaf is finite."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.asarray(True)
    return jnp.stack([_tensor_finite(x) for x in leaves]).all()


def sanitize_captures(captures: dict) -> tuple[dict, jax.Array]:
    """Zero out non-finite per-call capture tensors; count them.

    The jit-friendly analogue of the reference's hook-time drop of
    inf/NaN grad-output batches (kfac/layers/base.py:397-407): a tensor
    with *any* non-finite element is replaced by zeros (whole-tensor,
    like the reference's whole-batch drop — partial masking would bias
    the covariance). Returns ``(clean_captures, n_zeroed)`` with
    ``n_zeroed`` an on-device int32 count suitable for metrics.
    """
    count = jnp.zeros((), jnp.int32)
    out = {}
    for name, entry in captures.items():
        clean = {}
        # Every capture stream, not just the primary 'a'/'g' pair — a
        # tied embedding's 'a_tied'/'g_tied' attend streams (r13) feed
        # the same factor statistics and need the same NaN hygiene.
        for key, calls_in in entry.items():
            calls = []
            for x in calls_in:
                ok = _tensor_finite(x)
                count = count + jnp.where(ok, 0, 1).astype(jnp.int32)
                calls.append(jnp.where(ok, x, jnp.zeros_like(x)))
            clean[key] = tuple(calls)
        out[name] = clean
    return out, count


def init_loss_scale(initial: float = 2.0 ** 15) -> dict:
    """Fresh dynamic-loss-scale state (AMP GradScaler defaults)."""
    return {'scale': jnp.asarray(initial, jnp.float32),
            'growth_count': jnp.zeros((), jnp.int32)}


def update_loss_scale(state: dict, grads_finite,
                      growth_interval: int = 2000,
                      growth_factor: float = 2.0,
                      backoff_factor: float = 0.5,
                      min_scale: float = 1.0,
                      max_scale: float = 2.0 ** 24) -> dict:
    """One GradScaler schedule step (pure).

    ``grads_finite``: scalar bool (e.g. ``tree_all_finite(grads)``).
    On overflow the scale backs off and the growth counter resets; after
    ``growth_interval`` consecutive finite steps the scale doubles.
    The *caller* skips the parameter update on overflow (see
    :func:`apply_if_finite`).
    """
    grads_finite = jnp.asarray(grads_finite)
    grew = state['growth_count'] + 1
    do_grow = grads_finite & (grew >= growth_interval)
    new_scale = jnp.where(
        grads_finite,
        jnp.where(do_grow, state['scale'] * growth_factor,
                  state['scale']),
        state['scale'] * backoff_factor)
    new_scale = jnp.clip(new_scale, min_scale, max_scale)
    new_count = jnp.where(grads_finite & ~do_grow, grew, 0)
    return {'scale': new_scale, 'growth_count': new_count}


def apply_if_finite(grads_finite, new_tree, old_tree):
    """Select ``new_tree`` when grads were finite, else keep ``old_tree``.

    The jit form of GradScaler's skipped ``optimizer.step()`` on
    overflow: apply to (params, opt_state, kfac_state, ...) pairs.
    """
    grads_finite = jnp.asarray(grads_finite)
    return jax.tree.map(
        lambda n, o: jnp.where(grads_finite, n, o), new_tree, old_tree)
