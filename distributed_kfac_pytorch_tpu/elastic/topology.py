"""Checkpoint topology metadata: the saving world, recorded in scalars.

Elastic resume (ROADMAP open item 4) turns the pod topology into a
resume-time parameter: a run saved on an N-device mesh can restore onto
an M-device mesh, with the K-FAC state re-sharded on the way in
(:mod:`elastic.reshard`). The enabler is that every checkpoint bundle
records the topology that SAVED it — this module is that record.

What the K-FAC state layout actually depends on (and therefore what a
resharder must know) is the KAISA work-placement grid, not the raw
device list: ``assign_work`` is a deterministic function of
``(layer specs, n_rows, n_cols, distribute_layer_factors)``
(parallel/distributed.py), so those three integers-and-a-bool pin the
exact slot position of every factor in every row-sharded bucket stack.
Process/device counts ride along for diagnostics and the
``topology_change`` event. State-group shardings are structural and
constant across topologies (``inv_stacks`` row-sharded over
``kfac_ig``, everything else replicated — ``state_pspecs``), so they
are documented rather than recorded.

The scalars are plain ints (``topo_*`` keys) inside the bundle's
existing ``scalars`` subtree, so orbax round-trips them untouched and
the bundle format bump is additive (MIGRATION.md "Checkpoint format"):
bundles written before this extension simply lack the keys and are
treated as *same-topology-only* on restore.
"""

from __future__ import annotations

import dataclasses

# Bumped if the meaning of the recorded fields ever changes; readers
# treat unknown future formats as same-topology-only rather than
# resharding on semantics they do not understand.
TOPOLOGY_FORMAT = 1

#: scalar keys this module owns inside a bundle's ``scalars`` subtree.
SCALAR_KEYS = ('topo_format', 'topo_processes', 'topo_devices',
               'topo_rows', 'topo_cols', 'topo_seq', 'topo_slices',
               'topo_dist_factors')


@dataclasses.dataclass(frozen=True)
class TopologySpec:
    """The world a checkpoint was saved on (or a live mesh's world).

    ``rows``/``cols`` are the KAISA grid — inverse-broadcast groups and
    grad workers per group (``placement.WorkerAllocator``) — and, on a
    multi-slice mesh (r20), ``rows`` is the PER-SLICE group count:
    ``slices`` counts the outer ``kfac_slice`` dimension and the global
    row space is ``slices * rows``. ``distribute_layer_factors`` is the
    *effective* A/G-on-different-columns flag (the ``assign_work``
    default resolves ``None`` to ``cols > 1``, so the recorded value
    is always a concrete bool). Bundles saved before r20 lack the
    ``topo_slices`` scalar and default to 1 slice (MIGRATION.md).
    """
    processes: int
    devices: int
    rows: int
    cols: int
    seq: int = 1
    slices: int = 1
    distribute_layer_factors: bool = True

    def __post_init__(self):
        if self.slices < 1:
            raise ValueError(f'inconsistent topology: slices '
                             f'{self.slices} must be >= 1')
        if self.rows * self.cols * self.seq * self.slices != self.devices:
            raise ValueError(
                f'inconsistent topology: rows {self.rows} x cols '
                f'{self.cols} x seq {self.seq} x slices {self.slices} '
                f'!= devices {self.devices}')

    @property
    def layout_key(self) -> tuple:
        """The part of the spec the K-FAC state layout depends on.

        Worlds with equal layout keys produce byte-compatible state
        trees (same bucket slot maps, same stack shapes) even when the
        process count or sequence-parallel factor differs — restore
        then needs only the existing sharding re-commit, no reshard.
        ``assign_work`` places over the GLOBAL row count
        ``slices * rows``, so slice-count changes that preserve the
        global row total (e.g. 2 slices x 2 rows -> 1 slice x 4 rows)
        are layout-preserving too.
        """
        return (self.slices * self.rows, self.cols,
                self.distribute_layer_factors)

    def needs_reshard(self, other: 'TopologySpec') -> bool:
        return self.layout_key != other.layout_key

    def scalars(self) -> dict:
        """``topo_*`` int scalars to merge into a bundle's scalars."""
        return {'topo_format': TOPOLOGY_FORMAT,
                'topo_processes': int(self.processes),
                'topo_devices': int(self.devices),
                'topo_rows': int(self.rows),
                'topo_cols': int(self.cols),
                'topo_seq': int(self.seq),
                'topo_slices': int(self.slices),
                'topo_dist_factors': int(self.distribute_layer_factors)}

    @classmethod
    def from_scalars(cls, scalars: dict) -> 'TopologySpec | None':
        """Rebuild from a restored bundle's ``scalars`` (None when the
        bundle predates topology metadata, or records a future format
        — both mean same-topology-only)."""
        if not scalars or 'topo_format' not in scalars:
            return None
        if int(scalars['topo_format']) != TOPOLOGY_FORMAT:
            return None
        return cls(processes=int(scalars['topo_processes']),
                   devices=int(scalars['topo_devices']),
                   rows=int(scalars['topo_rows']),
                   cols=int(scalars['topo_cols']),
                   seq=int(scalars.get('topo_seq', 1)),
                   # Pre-r20 bundles predate multi-slice: 1 slice.
                   slices=int(scalars.get('topo_slices', 1)),
                   distribute_layer_factors=bool(
                       int(scalars['topo_dist_factors'])))

    @classmethod
    def of_mesh(cls, mesh, *,
                distribute_layer_factors: bool | None = None
                ) -> 'TopologySpec':
        """The live world of a ``make_kfac_mesh`` mesh.

        ``distribute_layer_factors`` takes the ``DistributedKFAC``
        value (``DistributedKFAC.distribute_layer_factors`` after
        construction); ``None`` resolves to the ``assign_work`` default
        (``cols > 1``) — pass the dkfac's attribute whenever one
        exists so the record matches the placement actually used.
        """
        import jax

        from distributed_kfac_pytorch_tpu.parallel.distributed import (
            GRAD_WORKER_AXIS,
            INV_GROUP_AXIS,
            SLICE_AXIS,
        )
        from distributed_kfac_pytorch_tpu.parallel.sequence import (
            SEQ_AXIS,
        )
        rows = mesh.shape[INV_GROUP_AXIS]
        cols = mesh.shape[GRAD_WORKER_AXIS]
        seq = (mesh.shape[SEQ_AXIS]
               if SEQ_AXIS in mesh.axis_names else 1)
        slices = (mesh.shape[SLICE_AXIS]
                  if SLICE_AXIS in mesh.axis_names else 1)
        if distribute_layer_factors is None:
            distribute_layer_factors = cols > 1
        return cls(processes=jax.process_count(),
                   devices=int(mesh.devices.size),
                   rows=int(rows), cols=int(cols), seq=int(seq),
                   slices=int(slices),
                   distribute_layer_factors=bool(
                       distribute_layer_factors))

    def describe(self) -> str:
        return (f'{self.devices} device(s) / {self.processes} '
                f'process(es), KAISA grid {self.rows}x{self.cols}'
                + (f' x seq {self.seq}' if self.seq > 1 else '')
                + (f', {self.slices} slice(s)'
                   if self.slices > 1 else ''))
