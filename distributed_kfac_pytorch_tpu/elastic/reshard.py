"""Re-shard K-FAC state between pod topologies at resume time.

The stateless-shard framing of *Distributed Preconditioning*
(arXiv:2206.15143): replicated factors let ANY world reconstruct its
preconditioning slice, so moving a run from an N-device mesh to an
M-device one is a pure resume-time transform — no cold restart.

Concretely, the topology-dependent part of ``DistributedKFAC`` state is
the row-sharded bucket stacks: each same-dim factor group lives as one
``(n_rows * slots_per_row, dim, dim)`` stack whose slot positions come
from the deterministic two-level LPT placement (``assign_work``). The
reshard is therefore a *permutation*, not a recomputation:

  1. **gather** — using the SAVED topology's ``WorkAssignment``
     (reconstructed host-side from the ``topo_*`` scalars the bundle
     recorded, :mod:`elastic.topology`), pull each ``(layer, 'A'|'G')``
     factor's inverse entries (``Q``/``d``/``inv``) out of the saved
     global stacks into a canonical per-factor layout;
  2. **repack** — place them at the NEW mesh's slot positions,
     identity/ones/zeros padding for unassigned slots exactly as
     ``init_state`` seeds them, and hand the result to the existing
     re-commit machinery (``DistributedKFAC.load_state_dict`` commits
     the stacks row-sharded; ``launch.replicate_on_mesh`` re-commits
     the replicated groups).

Because gather∘repack copies bytes, an N→M→N round trip is LOSSLESS:
resuming back on the original topology continues bit-identically to an
uninterrupted N-run (pinned by tests/test_elastic.py). Replicated
groups (factors, diagonal/grouped inverses, params, optimizer state)
pass through untouched; ``inv_chunk_phase`` rides along while the
chunk plan itself is re-planned implicitly — constructing
``DistributedKFAC`` on the new mesh reruns the greedy-LPT chunk
balance for the new device count, and the engine re-derives the firing
schedule from the step counter, so the zero-retrace guard holds on the
new world too.

Factor-only checkpoints (``include_inverses=False``) need none of
this: ``load_state_dict`` already rebuilds all inverse stacks from the
replicated factors — the purest form of the stateless-shard design.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

from distributed_kfac_pytorch_tpu.elastic.topology import TopologySpec


def saved_assignment(kfac, params, topo: TopologySpec):
    """The SAVED world's WorkAssignment, reconstructed host-side.

    ``assign_work`` is deterministic in ``(layer specs, params shapes,
    n_rows, n_cols, distribute_layer_factors)`` — all available on the
    restoring side — so the saving world's exact slot map can be
    rebuilt without ever having run there.
    """
    from distributed_kfac_pytorch_tpu.parallel.distributed import (
        assign_work,
    )
    from distributed_kfac_pytorch_tpu.parallel.placement import (
        WorkerAllocator,
    )
    # Validate the recorded grid as a legal KAISA partition first: the
    # allocator is the golden topology spec (reference kfac/utils.py),
    # and a bundle whose rows x cols cannot form one must fail here,
    # not deep inside the slot math. On a multi-slice world the
    # per-slice grid is the allocator unit; placement then runs over
    # the GLOBAL row space (slices * rows — each slice owns a
    # contiguous run of rows, exactly like the live DistributedKFAC).
    alloc = WorkerAllocator.from_grid(topo.rows, topo.cols)
    assert (alloc.inv_groups, alloc.grad_workers) == (topo.rows,
                                                      topo.cols)
    return assign_work(
        kfac, params, topo.slices * topo.rows, topo.cols,
        distribute_layer_factors=topo.distribute_layer_factors)


def _to_host(x) -> np.ndarray:
    """Host view of a (fully-addressable) array leaf."""
    return np.asarray(x)


def gather_canonical(inv_stacks: dict, assignment) -> dict:
    """Saved slot stacks -> canonical ``{(name, 'A'|'G'): {key: mat}}``.

    ``assignment`` must be the SAVED topology's (``saved_assignment``);
    shapes are validated against it so a bundle whose stacks do not
    match its recorded topology fails loudly instead of scattering
    garbage.
    """
    canon: dict[tuple, dict] = {}
    for dim, plan in assignment.buckets.items():
        entry = inv_stacks[str(dim)]
        S = plan.slots_per_row
        n_slots = assignment.n_rows * S
        arrs = {}
        for key, stack in entry.items():
            host = _to_host(stack)
            if host.shape[0] != n_slots:
                raise ValueError(
                    f'checkpoint inv_stacks[{dim}][{key!r}] has '
                    f'{host.shape[0]} slots but the recorded topology '
                    f'implies {n_slots} — the bundle does not match '
                    'its own topo_* scalars (corrupt or hand-edited '
                    'checkpoint)')
            arrs[key] = host
        for (name, which), slot in plan.slot.items():
            g = assignment.layer_row[name] * S + slot
            canon[(name, which)] = {k: v[g] for k, v in arrs.items()}
    return canon


def _pad_stack(key: str, n_slots: int, shape: tuple, dtype) -> np.ndarray:
    """Padding slots seeded exactly like ``init_state``: identity
    eigenbases / unit eigenvalues (a valid warm start for the polish),
    zero dense inverses. A truncated (dim, r) basis (r19 low-rank)
    pads with the rectangular identity-column seed — assigned slots
    carry their saved bases across the reshard unchanged."""
    if key == 'Q':
        # np.eye(N, M): rectangular for truncated bases, square else.
        return np.broadcast_to(np.eye(shape[-2], shape[-1],
                                      dtype=dtype),
                               (n_slots,) + shape).copy()
    if key == 'd':
        return np.ones((n_slots,) + shape, dtype)
    return np.zeros((n_slots,) + shape, dtype)


def repack_canonical(canon: dict, assignment) -> dict:
    """Canonical per-factor entries -> the NEW topology's slot stacks."""
    stacks: dict[str, dict] = {}
    for dim, plan in assignment.buckets.items():
        S = plan.slots_per_row
        n_slots = assignment.n_rows * S
        sample_key = next(iter(plan.slot))
        if sample_key not in canon:
            raise ValueError(
                f'factor {sample_key} missing from the gathered '
                'checkpoint state — saved and live layer registries '
                'disagree (layer congruence should have caught this)')
        arrs = {k: _pad_stack(k, n_slots, v.shape, v.dtype)
                for k, v in canon[sample_key].items()}
        for (name, which), slot in plan.slot.items():
            g = assignment.layer_row[name] * S + slot
            for k, mat in canon[(name, which)].items():
                arrs[k][g] = mat
        stacks[str(dim)] = arrs
    return stacks


def reshard_state_dict(sd: dict, saved_topo: TopologySpec, dkfac,
                       params) -> dict:
    """A ``DistributedKFAC.state_dict`` tree, re-sharded for ``dkfac``'s
    live mesh.

    ``sd`` leaves must be host or fully-addressable (e.g. replicated)
    arrays — the elastic restore path guarantees this
    (``CheckpointManager.restore_replicated``). Replicated groups
    (step, factors, diag/grouped inverses, ``inv_chunk_phase``) pass
    through; only ``inv_stacks`` is gathered and repacked. The result
    feeds straight into ``DistributedKFAC.load_state_dict``, whose
    ``_commit_host_leaves`` commits the new stacks row-sharded.
    """
    kfac = dkfac.kfac
    if set(sd.get('factors', {})) != set(kfac.specs):
        raise ValueError(
            'cannot reshard: checkpoint layers do not match registered '
            f'layers: {sorted(sd.get("factors", {}))} vs '
            f'{sorted(kfac.specs)}')
    live = TopologySpec.of_mesh(
        dkfac.mesh,
        distribute_layer_factors=dkfac.distribute_layer_factors)
    if not saved_topo.needs_reshard(live):
        return sd
    if 'inv_stacks' not in sd:
        # Factor-only checkpoint: nothing topology-shaped to move;
        # load_state_dict recomputes the inverses from the replicated
        # factors on the new mesh (the stateless-shard fast path).
        return sd
    if not _stacks_match_config(sd['inv_stacks'], dkfac):
        # The saved inverse REPRESENTATION does not match the live
        # config (e.g. eigen stacks saved, 'inv' dispatch resumed) —
        # the same cross-config case load_state_dict already degrades
        # on: drop the inverse groups so it rebuilds everything from
        # the (topology-independent) replicated factors.
        return {k: v for k, v in sd.items()
                if k not in ('inv_stacks', 'diag_inv', 'grouped_inv')}
    assn = saved_assignment(kfac, params, saved_topo)
    canon = gather_canonical(sd['inv_stacks'], assn)
    return {**sd,
            'inv_stacks': repack_canonical(canon, dkfac.assignment)}


def _stacks_match_config(inv_stacks: dict, dkfac) -> bool:
    """Do the saved stacks carry exactly the entry keys the live
    config's dispatch produces? Bucket dims and per-dim Q/d/inv key
    sets are functions of (model, K-FAC config) — NOT of topology —
    so a mismatch here means the run configuration changed, which is
    rebuild-from-factors territory, not reshard territory."""
    from distributed_kfac_pytorch_tpu.preconditioner import eigen_family
    kfac = dkfac.kfac
    expected = {}
    for dim in dkfac.assignment.buckets:
        if eigen_family(kfac.method_for_dim(dim)):
            keys = {'Q', 'd'}
            if dkfac._bucket_mixed.get(dim):
                keys.add('inv')
        else:
            keys = {'inv'}
        expected[str(dim)] = keys
    # r19: a low-rank basis saved at a DIFFERENT rank shares the Q/d
    # key names; the per-slot column count must also line up or the
    # repacked stacks feed wrong-shape operands to the firing —
    # rebuild from factors instead (reseed, not carry).
    for dim in dkfac.assignment.buckets:
        entry = inv_stacks.get(str(dim))
        if not entry or 'Q' not in entry:
            continue
        rank = kfac.lowrank_rank_for(dim) or dim
        if tuple(np.shape(entry['Q']))[-2:] != (dim, rank):
            return False
    return {k: set(v) for k, v in inv_stacks.items()} == expected


# ---------------------------------------------------------------------------
# Resume-time context (consumed by resilience.cli.resume)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ElasticResume:
    """Everything the elastic resume path needs about the LIVE world.

    ``dkfac`` may be None (SGD baseline runs): there is no K-FAC state
    to reshard, but restored replicated groups are still re-committed
    onto the new mesh. ``params`` is the live parameter template
    (needed to reconstruct the saved WorkAssignment).
    """
    mesh: Any
    dkfac: Any = None
    params: Any = None

    @property
    def topology(self) -> TopologySpec:
        return TopologySpec.of_mesh(
            self.mesh,
            distribute_layer_factors=(
                self.dkfac.distribute_layer_factors
                if self.dkfac is not None else None))

    def reshard_tree(self, tree: dict,
                     saved_topo: TopologySpec | None) -> dict:
        """Re-shard a restored bundle for the live world.

        The kfac subtree goes through :func:`reshard_state_dict` (when
        a reshard is needed and possible); the replicated groups are
        re-committed onto the live mesh via
        ``launch.replicate_on_mesh`` — the restore handed them back
        replicated-but-host-staged, and an uncommitted splice would
        re-shard lazily inside the first jitted step (or worse, break
        the next ``bundle_fn`` template on a pod).
        """
        from distributed_kfac_pytorch_tpu import launch

        out = dict(tree)
        if (self.dkfac is not None and out.get('kfac')
                and saved_topo is not None):
            out['kfac'] = reshard_state_dict(
                out['kfac'], saved_topo, self.dkfac, self.params)
        for key in ('params', 'opt_state', 'extra_vars'):
            if key in out:
                out[key] = launch.replicate_on_mesh(self.mesh, out[key])
        return out


def like_matches_metadata(metadata, like) -> bool:
    """Do the saved leaves' shapes line up with the live template's?

    A conservative positional comparison (leaf count + per-leaf
    shapes): metadata trees come back from orbax in plain containers,
    so treedefs cannot be compared directly against a live template
    holding custom nodes (optax states). A false positive is caught by
    the caller's try/except around the ``like=`` restore; a false
    negative just routes through the (always-correct) replicated
    restore.
    """
    import jax

    try:
        m_leaves = jax.tree.leaves(metadata)
        l_leaves = jax.tree.leaves(like)
    except Exception:
        return False
    if len(m_leaves) != len(l_leaves):
        return False
    return all(
        tuple(getattr(m, 'shape', ()) or ()) == tuple(np.shape(l))
        for m, l in zip(m_leaves, l_leaves))
