"""Elastic mesh training: topology as a resume-time parameter.

Production fleets gain and lose slices constantly; this subsystem lets
a checkpointed run resume on a DIFFERENT pod topology instead of cold
restarting (ROADMAP open item 4):

  - :mod:`elastic.topology` — every checkpoint bundle records the
    saving world (``topo_*`` scalars);
  - :mod:`elastic.reshard` — gather the saved KAISA slot stacks to a
    canonical per-factor layout and repack them for the new mesh (a
    lossless permutation, so N→M→N resumes are bit-identical);
  - the resume integration lives in ``resilience.cli.resume``
    (pass ``elastic=ElasticResume(mesh=..., dkfac=..., params=...)``),
    and the ``resize@K->N`` fault kind in ``resilience.faults`` +
    ``resilience.chaos`` makes the whole grow/shrink loop testable on
    CPU.

See README "Elastic training" for the walkthrough and the N→M→N
contract.
"""

from distributed_kfac_pytorch_tpu.elastic.reshard import (  # noqa: F401
    ElasticResume,
    reshard_state_dict,
)
from distributed_kfac_pytorch_tpu.elastic.topology import (  # noqa: F401
    TopologySpec,
)
