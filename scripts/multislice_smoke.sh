#!/usr/bin/env bash
# Fast-tier multi-slice smoke (r20): the two-level collective topology
# end to end on CPU through the REAL LM entry point —
#   1. a 2-slice x 4-device nested-mesh run (--num-slices 2
#      --hierarchical-reduce) under the full runtime sanitizer
#      (KFAC_SANITIZE=transfer,nan,retrace): factors pmean on-slice
#      every factor step, the cross-slice (DCN) reduce fires only on
#      r14 window heads — assert the stream shows the hierarchical
#      schedule (fired stages carrying 'dcn_reduce', ZERO retrace
#      events);
#   2. slice-loss failover (chaos slice-loss@1->1): drain a 2-slice
#      8-device run, relaunch on the single survivor slice (4 devices,
#      KFAC_NUM_SLICES exported by the harness so the CLI's
#      --num-slices default follows), resume through the elastic
#      reshard path — assert topology_change 8->4 with resharded=true
#      and global steps continuing, not restarting;
#   3. observability-gate self-check over the hierarchical stream (the
#      CI plumbing path, like overlap_smoke.sh's leg 2).
# The same contracts are pinned in tests/test_multislice.py; this
# wrapper is the standalone/CI-pipeline form.
set -euo pipefail
cd "$(dirname "$0")/.."

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

echo "== leg 1: 2-slice x 4-device hierarchical-reduce run =="
# Compile cache OFF: multi-device CPU warm reads are the known-
# segfaulting combination (see tests/conftest.py).
env JAX_PLATFORMS=cpu KFAC_COMPILE_CACHE=0 KFAC_SYNTHETIC_LM=2048 \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    KFAC_SANITIZE=transfer,nan,retrace \
python examples/train_language_model.py \
    --arch transformer --emsize 64 --nlayers 1 --nheads 2 \
    --bptt 16 --batch-size 8 --epochs 1 --no-resume \
    --num-slices 2 --hierarchical-reduce --kfac-update-freq 8 \
    --log-dir "$out/logs-hier" --checkpoint-dir "$out/ckpt-hier" \
    --kfac-metrics "$out/hier.jsonl" --metrics-interval 1

python - "$out/hier.jsonl" <<'EOF'
import sys
from distributed_kfac_pytorch_tpu.observability import sink as obs_sink

records, _ = obs_sink.read_jsonl_tolerant(sys.argv[1])
fired = [r.get('fired') for r in records if r.get('kind') == 'step']
dcn = [f for f in fired if f and 'dcn_reduce' in f]
assert dcn, fired        # cross-slice reduce fired on window heads
# No window head may carry a PLAIN 'reduce': every deferred boundary
# of a hierarchical run is the DCN one.
assert not any(f and 'reduce' in f and 'dcn_reduce' not in f
               for f in fired), fired
retraces = [r for r in records if r.get('event') == 'retrace']
assert not retraces, retraces   # zero retraces on the nested mesh
print(f'hierarchical schedule OK ({len(dcn)} DCN window(s), '
      'zero retraces)')
EOF

echo "== leg 2: slice-loss failover (2 slices -> 1 survivor) =="
# KFAC_NUM_SLICES (not --num-slices) carries the slice count so the
# chaos harness can rewrite it for the relaunch: slice-loss@1->1
# drains at step 1, halves the forced world to the survivor slice and
# exports KFAC_NUM_SLICES=1 — the resumed run reshards elastically.
env JAX_PLATFORMS=cpu KFAC_COMPILE_CACHE=0 KFAC_SYNTHETIC_LM=2048 \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    KFAC_NUM_SLICES=2 \
python -m distributed_kfac_pytorch_tpu.resilience.chaos \
    'slice-loss@1->1' --relaunch 1 -- \
    python examples/train_language_model.py \
    --arch transformer --emsize 64 --nlayers 1 --nheads 2 \
    --bptt 16 --batch-size 8 --epochs 1 \
    --checkpoint-freq 1 --checkpoint-steps 1 \
    --log-dir "$out/logs-loss" --checkpoint-dir "$out/ckpt-loss" \
    --kfac-metrics "$out/loss.jsonl" --metrics-interval 1

python - "$out" <<'EOF'
import sys
from distributed_kfac_pytorch_tpu.observability import sink

out = sys.argv[1]
live = sink.read_jsonl(f'{out}/loss.jsonl')
steps = [r['step'] for r in live if r['kind'] == 'step']
events = [r['event'] for r in live if r['kind'] == 'event']
assert 'topology_change' in events and 'restore' in events, events
tc = next(r for r in live if r.get('event') == 'topology_change')
assert tc['data']['from_devices'] == 8, tc
assert tc['data']['to_devices'] == 4, tc
assert tc['data']['resharded'], tc
assert steps and steps[0] > 0, steps   # continued, not cold-restarted
prev = sink.read_incarnation(f'{out}/loss.jsonl.prev.1')
prev_events = [r.get('event') for r in prev if r['kind'] == 'event']
assert 'preemption' in prev_events, prev_events
print('slice-loss failover OK (8->4 devices, elastic resume, steps '
      f'continued at {steps[0]})')
EOF
# The report schema-validates both incarnations (non-zero exit fails
# the smoke).
python -m distributed_kfac_pytorch_tpu.observability.report \
    "$out/loss.jsonl"

echo "== leg 3: gate self-check over the hierarchical stream =="
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/hier.jsonl" --write-baseline "$out/B.json"
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/hier.jsonl" --baseline "$out/B.json" --allow-missing \
    --json > "$out/gate.json"
python - "$out/gate.json" <<'EOF'
import json, sys
v = json.load(open(sys.argv[1]))
assert v['pass'] is True, v
print('gate self-check OK')
EOF

echo "multislice smoke OK"
