#!/usr/bin/env bash
# Fast-tier fused hot-path kernel smoke (r21): both Pallas knobs end
# to end on CPU (interpret mode) through the REAL LM entry point —
#   1. one tiny synthetic-corpus epoch with --fused-factor-contraction
#      AND --fused-precondition engaged, under the full runtime
#      sanitizer (KFAC_SANITIZE=transfer,nan,retrace), metrics sink
#      on; assert finite losses, inverse firings, ZERO retrace events
#      and ZERO pallas_fallback events with both kernels live;
#   2. observability-gate self-check over the stream (the CI plumbing
#      path, like lowrank_smoke.sh's leg 2);
#   3. forced-fallback leg: KFAC_PALLAS_FALLBACK=1 must still train
#      (stock XLA path) AND surface the named pallas_fallback events
#      in the stream — a failed probe is recorded, never silent.
# The same contracts are pinned in tests/test_fused_kernels.py; this
# wrapper is the standalone/CI-pipeline form (see lowrank_smoke.sh).
set -euo pipefail
cd "$(dirname "$0")/.."

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

run_lm() {  # $1 = leg name, extra args follow
    local leg="$1"; shift
    JAX_PLATFORMS=cpu KFAC_COMPILE_CACHE=0 KFAC_SYNTHETIC_LM=2048 \
    python examples/train_language_model.py \
        --arch transformer --emsize 64 --nlayers 1 --nheads 2 \
        --bptt 16 --batch-size 4 --epochs 1 --no-resume \
        --kfac-update-freq 4 \
        --log-dir "$out/logs-$leg" --checkpoint-dir "$out/ckpt-$leg" \
        "$@"
}

# Leg 1: both kernels engaged (interpret mode on CPU) under the full
# sanitizer, metrics at interval 1.
KFAC_SANITIZE=transfer,nan,retrace \
run_lm fused \
    --fused-factor-contraction --fused-precondition \
    --kfac-metrics "$out/fused.jsonl" --metrics-interval 1

python - "$out/fused.jsonl" <<'EOF'
import math
import sys

from distributed_kfac_pytorch_tpu.observability import sink as obs_sink

path = sys.argv[1]
records, _ = obs_sink.read_jsonl_tolerant(path)
steps = [r for r in records if r.get('kind') == 'step']
assert steps, 'no step records in the metrics stream'
fired = [r.get('fired') for r in steps]
assert 'inverse' in fired, fired
assert all(math.isfinite(float(r['loss'])) for r in steps
           if 'loss' in r), 'non-finite loss with fused kernels'
retraces = [r for r in records if r.get('event') == 'retrace']
assert not retraces, retraces           # zero retraces, kernels live
fallbacks = [r for r in records
             if r.get('event') == 'pallas_fallback']
assert not fallbacks, fallbacks         # probes passed: no fallback
print(f'fused kernels OK ({len(steps)} steps, zero retraces, '
      'zero fallbacks)')
EOF

# Leg 2: gate self-check (stream is gate-clean against itself).
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/fused.jsonl" --write-baseline "$out/B.json"
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/fused.jsonl" --baseline "$out/B.json" --allow-missing \
    --json > "$out/gate.json"
python - "$out/gate.json" <<'EOF'
import json, sys
v = json.load(open(sys.argv[1]))
assert v['pass'] is True, v
print('gate self-check OK')
EOF

# Leg 3: forced fallback — the kill switch must keep training on the
# stock XLA path and record NAMED pallas_fallback events in the
# stream (never a silent degrade).
KFAC_SANITIZE=transfer,nan,retrace KFAC_PALLAS_FALLBACK=1 \
run_lm fallback \
    --fused-factor-contraction --fused-precondition \
    --kfac-metrics "$out/fallback.jsonl" --metrics-interval 1

python - "$out/fallback.jsonl" <<'EOF'
import math
import sys

from distributed_kfac_pytorch_tpu.observability import sink as obs_sink

records, _ = obs_sink.read_jsonl_tolerant(sys.argv[1])
steps = [r for r in records if r.get('kind') == 'step']
assert steps, 'no step records in the forced-fallback stream'
assert all(math.isfinite(float(r['loss'])) for r in steps
           if 'loss' in r), 'non-finite loss on the fallback path'
fallbacks = [r for r in records
             if r.get('event') == 'pallas_fallback']
kernels = sorted({r.get('data', {}).get('kernel')
                  for r in fallbacks})
assert 'factor_ema' in kernels and 'bucket_precond' in kernels, (
    'forced fallback did not record both kernels', kernels)
print(f'forced-fallback leg OK (events for {kernels})')
EOF

echo 'pallas_smoke: all legs OK'
