#!/usr/bin/env bash
# Kill-and-resume smoke (r8): preempt a tiny CIFAR run mid-epoch via an
# injected fault, relaunch it, and assert the combined per-step loss
# sequence is BIT-IDENTICAL to an uninterrupted run's. The same check
# runs in the test suite as
# tests/test_resilience.py::TestCLIKillAndResume (full tier); this
# wrapper is the standalone/CI-pipeline form.
#
# One-command equivalent (single metrics file, relaunch handled by the
# chaos harness):
#   python -m distributed_kfac_pytorch_tpu.resilience.chaos \
#       'preempt@1' --relaunch 1 -- python examples/train_cifar10_resnet.py ...
# The two launches are driven explicitly below so each gets its own
# metrics JSONL (a fresh sink owns its path).
set -euo pipefail
cd "$(dirname "$0")/.."

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

# One shared compile cache: the relaunch recompiles the identical
# program, so runs 2-3 are warm (single-device CPU warm reads are fine;
# see utils.enable_compilation_cache for the multi-device caveat).
common_env=(JAX_PLATFORMS=cpu KFAC_SYNTHETIC_CIFAR=384
            KFAC_COMPILE_CACHE="$out/cache")
common_args=(--epochs 1 --model resnet20
             --batch-size 128 --val-batch-size 96
             --kfac-update-freq 1 --kfac-cov-update-freq 1
             --checkpoint-steps 1 --metrics-interval 1
             --log-dir "$out/logs")

echo "== reference (uninterrupted) run =="
env "${common_env[@]}" python examples/train_cifar10_resnet.py \
    "${common_args[@]}" --no-resume \
    --checkpoint-dir "$out/ckpt-ref" \
    --kfac-metrics "$out/ref.jsonl"

echo "== preempted run (injected preemption after step 1) =="
set +e
env "${common_env[@]}" KFAC_CHAOS='preempt@1' \
python examples/train_cifar10_resnet.py "${common_args[@]}" \
    --checkpoint-dir "$out/ckpt" --kfac-metrics "$out/run1.jsonl"
rc=$?
set -e
[ "$rc" -eq 75 ] || { echo "expected exit 75 (preempted), got $rc"; exit 1; }

echo "== relaunch (auto-resume from the step checkpoint) =="
env "${common_env[@]}" python examples/train_cifar10_resnet.py \
    "${common_args[@]}" --checkpoint-dir "$out/ckpt" \
    --kfac-metrics "$out/run2.jsonl"

echo "== comparing per-step loss sequences =="
python - "$out" <<'EOF'
import sys
from distributed_kfac_pytorch_tpu.observability import sink

out = sys.argv[1]
losses = lambda p: [(r['step'], r['metrics']['loss'])
                    for r in sink.read_jsonl(p) if r['kind'] == 'step']
ref = losses(f'{out}/ref.jsonl')
got = losses(f'{out}/run1.jsonl') + losses(f'{out}/run2.jsonl')
assert len(ref) == 3, ref
assert got == ref, f'loss sequences diverged:\nref {ref}\ngot {got}'
events = [r['event'] for r in sink.read_jsonl(f'{out}/run1.jsonl')
          if r['kind'] == 'event']
assert 'preemption' in events and 'checkpoint_save' in events, events
print('kill-and-resume: per-step losses BIT-IDENTICAL to the '
      'uninterrupted run')
EOF

python -m distributed_kfac_pytorch_tpu.observability.report \
    "$out/run2.jsonl"

echo "== elastic resize leg (resize@1->2: drain a 4-device run, =="
echo "== relaunch with 2 devices, resume via the reshard path)  =="
# The chaos harness owns the whole loop: it injects the fault, sees the
# relaunch exit code, rewrites XLA_FLAGS to the new world size, and
# relaunches. Both launches share one metrics path — the drained
# incarnation survives as resize.jsonl.prev.1. Compile cache OFF for
# this leg: multi-device CPU warm reads are the known-segfaulting
# combination (see tests/conftest.py).
env JAX_PLATFORMS=cpu KFAC_SYNTHETIC_CIFAR=384 KFAC_COMPILE_CACHE=0 \
    XLA_FLAGS=--xla_force_host_platform_device_count=4 \
python -m distributed_kfac_pytorch_tpu.resilience.chaos \
    'resize@1->2' --relaunch 1 -- \
    python examples/train_cifar10_resnet.py "${common_args[@]}" \
    --checkpoint-dir "$out/ckpt-resize" \
    --kfac-metrics "$out/resize.jsonl"

echo "== checking the grow/shrink loop completed without a cold restart =="
python - "$out" <<'EOF'
import sys
from distributed_kfac_pytorch_tpu.observability import sink

out = sys.argv[1]
live = sink.read_jsonl(f'{out}/resize.jsonl')
steps = [r['step'] for r in live if r['kind'] == 'step']
events = [r['event'] for r in live if r['kind'] == 'event']
# The relaunch CONTINUED the run (global steps 1..2 after the drained
# step 0) instead of cold-restarting at 0, and the topology change was
# recorded alongside the restore.
assert steps == [1, 2], steps
assert 'topology_change' in events and 'restore' in events, events
tc = next(r for r in live if r.get('event') == 'topology_change')
assert tc['data']['from_devices'] == 4, tc
assert tc['data']['to_devices'] == 2, tc
assert tc['data']['resharded'], tc
prev = sink.read_incarnation(f'{out}/resize.jsonl.prev.1')
prev_events = [r.get('event') for r in prev if r['kind'] == 'event']
assert 'preemption' in prev_events, prev_events
print('resize leg: 4->2 grow/shrink loop resumed elastically '
      '(topology_change + restore recorded; steps continued 1..2)')
EOF
# The report surfaces the resize alongside the preemption/restore
# lifecycle (schema-validates the stream; non-zero exit fails the
# smoke).
python -m distributed_kfac_pytorch_tpu.observability.report \
    "$out/resize.jsonl"

echo "== supervisor pure-relaunch leg (r17): the same preempt-and- =="
echo "== resume loop, driven by the real failure supervisor        =="
# The chaos harness leg above hand-rolls the relaunch; this is the
# production form — the supervisor classifies the drain exit and
# relaunches with the checkpoint fresh (no backoff, no budget). Full
# failure-class coverage (crash/hang/failover/crash-loop) lives in
# scripts/supervisor_smoke.sh.
env "${common_env[@]}" KFAC_CHAOS='preempt@1' \
python -m distributed_kfac_pytorch_tpu.resilience.supervisor \
    --workdir "$out/sup" --metrics "$out/sup.jsonl" \
    --hang-timeout 90 --startup-grace 600 --backoff 0 -- \
    python examples/train_cifar10_resnet.py "${common_args[@]}" \
    --checkpoint-dir "$out/ckpt-sup" --kfac-metrics "$out/sup.jsonl"

python - "$out" <<'EOF'
import sys
from distributed_kfac_pytorch_tpu.observability import sink

out = sys.argv[1]
sup = [r for r in sink.read_jsonl(f'{out}/sup.jsonl.supervisor')
       if r['kind'] == 'event']
assert [r['event'] for r in sup] == ['supervisor_restart'], sup
assert sup[0]['data']['reason'] == 'drain', sup
steps = [r['step'] for r in sink.read_jsonl(f'{out}/sup.jsonl')
         if r['kind'] == 'step']
assert steps and steps[0] > 0, steps  # resumed, not cold-started
print('supervisor leg: drain classified, relaunch resumed mid-epoch')
EOF
echo "resilience smoke OK"
