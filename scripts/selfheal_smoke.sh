#!/usr/bin/env bash
# Self-healing smoke (r16): prove the fault-response escalation ladder
# end-to-end through the REAL LM CLI — chaos-injected faults must be
# survived IN-PROCESS with the documented escalate -> recover event
# sequences in the metrics JSONL:
#
#   leg 1  corrupt-factor@K  -> damping escalation, per-bucket
#          quarantine, factor re-accumulation, re-admit; run finishes
#          with finite losses (exit 0, no relaunch).
#   leg 2  diverge@K         -> damping escalation then decay back
#          (finite loss-spike injection, runs under the FULL sanitizer
#          including nan).
#   leg 3  corrupt-ckpt@K    -> the verified resume walk quarantines
#          the bit-rotted bundle (ckpt_quarantine) and restores the
#          older verified one.
#   leg 4  rollback          -> with quarantine disabled, persistent
#          corruption escalates to an in-process rollback onto the
#          newest verified pre-fault bundle, and training CONTINUES to
#          a clean exit in the same process; the regression gate
#          surfaces the rollback count.
#
# Sanitizer note: legs 1 and 4 inject Inf into live state BY DESIGN, so
# they run under KFAC_SANITIZE=transfer,retrace (debug_nans would abort
# on the injected values the ladder exists to survive); legs 2-3 keep
# the full transfer,nan,retrace oracle.
set -euo pipefail
cd "$(dirname "$0")/.."

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

# ~31 optimizer steps per epoch (2000 tokens / batch 8 / bptt 8).
common_env=(JAX_PLATFORMS=cpu KFAC_COMPILE_CACHE=0 KFAC_SYNTHETIC_LM=2000)
common_args=(--arch lstm --emsize 16 --nhid 16 --nlayers 1
             --bptt 8 --batch-size 8 --epochs 1 --dropout 0.0
             --kfac-update-freq 4 --kfac-cov-update-freq 1
             --metrics-interval 1 --log-dir "$out/logs"
             --selfheal)

echo "== leg 1: corrupt-factor\@5 — quarantine -> re-admit in-process =="
env "${common_env[@]}" KFAC_CHAOS='corrupt-factor@5' \
    KFAC_SANITIZE=transfer,retrace \
python examples/train_language_model.py "${common_args[@]}" \
    --checkpoint-dir "$out/ckpt-cf" --no-resume \
    --kfac-metrics "$out/corrupt_factor.jsonl"

python - "$out" <<'EOF'
import math, sys
from distributed_kfac_pytorch_tpu.observability import sink
out = sys.argv[1]
recs = sink.read_jsonl(f'{out}/corrupt_factor.jsonl')
events = [r['event'] for r in recs if r['kind'] == 'event']
for want in ('selfheal_escalate', 'selfheal_quarantine',
             'selfheal_readmit', 'selfheal_deescalate'):
    assert want in events, (want, events)
# escalate -> quarantine -> readmit, in that order
assert events.index('selfheal_escalate') \
    < events.index('selfheal_quarantine') \
    < events.index('selfheal_readmit'), events
assert 'retrace' not in events, events  # zero retraces, ladder armed
losses = [float(r['metrics']['loss']) for r in recs
          if r['kind'] == 'step']
assert losses and all(math.isfinite(v) for v in losses), losses[-5:]
print(f'leg 1 OK: {events.count("selfheal_escalate")} escalation(s), '
      'quarantine -> re-admit, all losses finite')
EOF

echo "== leg 2: diverge\@5 — damping escalates then decays (full sanitizer) =="
# Cross-entropy saturates near log(vocab), so the spike is additive,
# not multiplicative — the divergence ratio is tuned down accordingly
# (the knob exists for exactly this workload dependence).
env "${common_env[@]}" KFAC_CHAOS='diverge@5' \
    KFAC_SANITIZE=transfer,nan,retrace \
python examples/train_language_model.py "${common_args[@]}" \
    --selfheal-diverge-ratio 1.3 \
    --checkpoint-dir "$out/ckpt-dv" --no-resume \
    --kfac-metrics "$out/diverge.jsonl"

python - "$out" <<'EOF'
import sys
from distributed_kfac_pytorch_tpu.observability import sink
out = sys.argv[1]
recs = sink.read_jsonl(f'{out}/diverge.jsonl')
events = [r['event'] for r in recs if r['kind'] == 'event']
assert 'selfheal_escalate' in events, events
assert 'selfheal_deescalate' in events, events
assert events.index('selfheal_escalate') \
    < events.index('selfheal_deescalate'), events
assert 'selfheal_quarantine' not in events, events  # finite fault
print('leg 2 OK: damping escalated then decayed back')
EOF

echo "== leg 3: corrupt-ckpt\@8 + crash\@9 — verified resume walks back =="
set +e
env "${common_env[@]}" KFAC_CHAOS='corrupt-ckpt@8,crash@9' \
    KFAC_SANITIZE=transfer,nan,retrace \
python examples/train_language_model.py "${common_args[@]}" \
    --checkpoint-steps 4 \
    --checkpoint-dir "$out/ckpt-cc" --no-resume \
    --kfac-metrics "$out/corrupt_ckpt1.jsonl"
rc=$?
set -e
[ "$rc" -eq 137 ] || { echo "expected exit 137 (crashed), got $rc"; exit 1; }

env "${common_env[@]}" KFAC_SANITIZE=transfer,nan,retrace \
python examples/train_language_model.py "${common_args[@]}" \
    --checkpoint-steps 4 \
    --checkpoint-dir "$out/ckpt-cc" \
    --kfac-metrics "$out/corrupt_ckpt2.jsonl"

python - "$out" <<'EOF'
import sys
from distributed_kfac_pytorch_tpu.observability import sink
out = sys.argv[1]
recs = sink.read_jsonl(f'{out}/corrupt_ckpt2.jsonl')
events = [(r['event'], r.get('data', {})) for r in recs
          if r['kind'] == 'event']
kinds = [e for e, _ in events]
assert 'ckpt_quarantine' in kinds, kinds
q = dict(events[kinds.index('ckpt_quarantine')][1])
assert q['label'] == 8, q       # the bit-rotted bundle
restore = dict(events[kinds.index('restore')][1])
assert restore['label'] == 4, restore  # the older VERIFIED bundle
steps = [r['step'] for r in recs if r['kind'] == 'step']
assert steps and steps[0] == 4, steps[:3]  # continued from step 4
print('leg 3 OK: corrupt bundle 8 quarantined, resumed from verified '
      'bundle 4')
EOF

echo "== leg 4: rollback — no quarantine, restore last-good IN-PROCESS =="
env "${common_env[@]}" KFAC_CHAOS='corrupt-factor@5' \
    KFAC_SANITIZE=transfer,retrace \
python examples/train_language_model.py "${common_args[@]}" \
    --selfheal-no-quarantine --selfheal-window 1 \
    --checkpoint-steps 2 \
    --checkpoint-dir "$out/ckpt-rb" --no-resume \
    --kfac-metrics "$out/rollback.jsonl"

python - "$out" <<'EOF'
import math, sys
from distributed_kfac_pytorch_tpu.observability import gate, sink
out = sys.argv[1]
recs = sink.read_jsonl(f'{out}/rollback.jsonl')
events = [(r['event'], r.get('data', {})) for r in recs
          if r['kind'] == 'event']
kinds = [e for e, _ in events]
assert 'selfheal_rollback' in kinds, kinds
rb = dict(events[kinds.index('selfheal_rollback')][1])
assert rb['to_step'] < rb['from_step'], rb
# The run CONTINUED past the rollback in the same process: step
# records exist beyond the rollback's from_step, and the tail is
# finite (the fault latch is one-shot, so the replay is clean).
steps = [r['step'] for r in recs if r['kind'] == 'step']
assert max(steps) > rb['from_step'], (max(steps), rb)
tail = [float(r['metrics']['loss']) for r in recs
        if r['kind'] == 'step' and r['step'] > rb['from_step']]
assert tail and all(math.isfinite(v) for v in tail)
# The gate surfaces the rollback as a countable metric.
m = gate.gate_metrics(recs)
assert m['selfheal_rollbacks'] == 1, m
assert m['retraces'] == 0, m
print(f'leg 4 OK: in-process rollback {rb["from_step"]} -> '
      f'{rb["to_step"]}, training continued to step {max(steps)}')
EOF

# The report must render the self-healing section for every leg and
# schema-validate the streams (non-zero exit fails the smoke).
# (grep over a captured file, not a pipe: grep -q closing the pipe
# early would SIGPIPE the report under pipefail.)
for leg in corrupt_factor diverge corrupt_ckpt2 rollback; do
    python -m distributed_kfac_pytorch_tpu.observability.report \
        "$out/$leg.jsonl" > "$out/$leg.report.txt"
    grep -q 'self-healing' "$out/$leg.report.txt" || {
        echo "report for $leg lacks the self-healing section"; exit 1; }
done
python -m distributed_kfac_pytorch_tpu.observability.report \
    "$out/rollback.jsonl"
echo "selfheal smoke OK"
