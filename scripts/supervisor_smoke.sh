#!/usr/bin/env bash
# Failure-supervision smoke (r17): every rung of the supervisor proven
# end-to-end on CPU through the real CIFAR CLI — crash relaunch, hang
# detection (lease expiry -> kill -> relaunch), survivor-mesh failover
# (capacity loss -> drain -> shrunken relaunch through the elastic
# resume), and crash-loop escalation with its distinct exit code. The
# LM-CLI variant rides in the test suite as
# tests/test_supervisor.py::TestLMCLISupervised (slow tier); this
# wrapper is the standalone/CI-pipeline form.
set -euo pipefail
cd "$(dirname "$0")/.."

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

# One shared compile cache for the single-device legs (warm relaunches;
# the multi-device leg runs cache-off — see tests/conftest.py for the
# multi-device warm-cache caveat).
common_env=(JAX_PLATFORMS=cpu KFAC_SYNTHETIC_CIFAR=384
            KFAC_COMPILE_CACHE="$out/cache")
common_args=(--epochs 1 --model resnet20
             --batch-size 128 --val-batch-size 96
             --kfac-update-freq 1 --kfac-cov-update-freq 1
             --checkpoint-steps 1 --metrics-interval 1
             --log-dir "$out/logs")
# --hang-timeout must outlast the child's longest lease-silent healthy
# stretch: the post-training eval + checkpoint tail (compile included)
# writes no leases. 90 s is ~3x the observed CPU tail.
sup_args=(--hang-timeout 90 --startup-grace 600 --poll 0.5
          --drain-grace 300 --backoff 0 --max-restarts 3)
supervisor=(python -m distributed_kfac_pytorch_tpu.resilience.supervisor)

echo "== leg 1: crash@2 — supervised relaunch to completion =="
env "${common_env[@]}" KFAC_CHAOS='crash@2' \
"${supervisor[@]}" --workdir "$out/sup-crash" --metrics "$out/crash.jsonl" \
    "${sup_args[@]}" -- \
    python examples/train_cifar10_resnet.py "${common_args[@]}" \
    --checkpoint-dir "$out/ckpt-crash" --kfac-metrics "$out/crash.jsonl"

python - "$out" <<'EOF'
import sys
from distributed_kfac_pytorch_tpu.observability import sink

out = sys.argv[1]
sup = [r for r in sink.read_jsonl(f'{out}/crash.jsonl.supervisor')
       if r['kind'] == 'event']
assert [r['event'] for r in sup] == ['supervisor_restart'], sup
assert sup[0]['data']['reason'] == 'crash', sup
# The relaunch RESUMED (the live stream starts past step 0) instead of
# cold-restarting.
steps = [r['step'] for r in sink.read_jsonl(f'{out}/crash.jsonl')
         if r['kind'] == 'step']
assert steps and steps[0] > 0, steps
print('crash leg: supervised relaunch resumed and completed')
EOF

echo "== gate: supervisor_restarts metric round-trips =="
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/crash.jsonl" --write-baseline "$out/base.json"
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/crash.jsonl" --baseline "$out/base.json" \
    --allow-missing --no-anomaly
python - "$out" <<'EOF'
import json, sys
base = json.load(open(f'{sys.argv[1]}/base.json'))
assert base['metrics']['supervisor_restarts'] == 1, base['metrics']
print('gate: supervisor_restarts recorded in the baseline vector')
EOF

echo "== leg 2: hang@2 — lease expiry, kill-and-relaunch =="
env "${common_env[@]}" KFAC_CHAOS='hang@2' \
"${supervisor[@]}" --workdir "$out/sup-hang" --metrics "$out/hang.jsonl" \
    "${sup_args[@]}" -- \
    python examples/train_cifar10_resnet.py "${common_args[@]}" \
    --checkpoint-dir "$out/ckpt-hang" --kfac-metrics "$out/hang.jsonl"

python - "$out" <<'EOF'
import sys
from distributed_kfac_pytorch_tpu.observability import sink

out = sys.argv[1]
sup = [r for r in sink.read_jsonl(f'{out}/hang.jsonl.supervisor')
       if r['kind'] == 'event']
assert [r['event'] for r in sup] == ['hang_detected',
                                     'supervisor_restart'], sup
assert sup[0]['data']['last_step'] == 2, sup
assert sup[1]['data']['reason'] == 'hang', sup
print('hang leg: lease expiry detected, wedged child killed, '
      'relaunch completed')
EOF

echo "== leg 3: failover-shrink — capacity 4 -> 2 through the =="
echo "==        elastic resume (supervisor_failover -> topology_change) =="
echo 2 > "$out/capacity"
env JAX_PLATFORMS=cpu KFAC_SYNTHETIC_CIFAR=384 KFAC_COMPILE_CACHE=0 \
"${supervisor[@]}" --workdir "$out/sup-shrink" --metrics "$out/shrink.jsonl" \
    "${sup_args[@]}" --devices 4 --capacity-file "$out/capacity" -- \
    python examples/train_cifar10_resnet.py "${common_args[@]}" \
    --checkpoint-dir "$out/ckpt-shrink" \
    --kfac-metrics "$out/shrink.jsonl"

python - "$out" <<'EOF'
import sys
from distributed_kfac_pytorch_tpu.observability import sink

out = sys.argv[1]
sup = [r for r in sink.read_jsonl(f'{out}/shrink.jsonl.supervisor')
       if r['kind'] == 'event']
assert [r['event'] for r in sup] == ['supervisor_failover'], sup
fo = sup[0]
assert fo['data']['from_devices'] == 4, fo
assert fo['data']['to_devices'] == 2, fo
live = sink.read_jsonl(f'{out}/shrink.jsonl')
tcs = [r for r in live if r.get('event') == 'topology_change']
assert tcs, [r.get('event') for r in live if r['kind'] == 'event']
tc = tcs[-1]
assert tc['data']['from_devices'] == 4, tc
assert tc['data']['to_devices'] == 2, tc
assert tc['data']['resharded'], tc
# The pinned SEQUENCE: the supervisor's failover decision precedes the
# relaunched child's elastic topology_change.
assert fo['wall_time'] <= tc['wall_time'], (fo, tc)
events = [r['event'] for r in live if r['kind'] == 'event']
assert 'restore' in events, events
print('failover leg: supervisor_failover -> topology_change 4->2, '
      'resumed via the elastic reshard (no cold restart)')
EOF

echo "== leg 4: crash loop — same step failing twice, distinct exit =="
set +e
env "${common_env[@]}" KFAC_CHAOS='crash@2' \
"${supervisor[@]}" --workdir "$out/sup-loop" --metrics "$out/loop.jsonl" \
    "${sup_args[@]}" --keep-faults --crash-loop-after 2 -- \
    python examples/train_cifar10_resnet.py "${common_args[@]}" \
    --checkpoint-dir "$out/ckpt-loop" --kfac-metrics "$out/loop.jsonl"
rc=$?
set -e
[ "$rc" -eq 77 ] || { echo "expected crash-loop exit 77, got $rc"; exit 1; }

python - "$out" <<'EOF'
import json, sys
from distributed_kfac_pytorch_tpu.observability import sink

out = sys.argv[1]
sup = [r for r in sink.read_jsonl(f'{out}/loop.jsonl.supervisor')
       if r['kind'] == 'event']
kinds = [r['event'] for r in sup]
assert kinds == ['supervisor_restart', 'crash_loop'], kinds
loop = sup[-1]['data']
assert loop['failure_step'] == 2 and loop['consecutive'] == 2, loop
diag = json.load(open(loop['diagnostic']))
assert diag['failure_step'] == 2 and diag['history'], diag
print('crash-loop leg: detected at step 2 after 2 launches, exit 77, '
      'diagnostic bundle written')
EOF

# The report's supervision section summarizes the whole session from
# the sidecar (schema-validates both streams; non-zero exit fails the
# smoke).
python -m distributed_kfac_pytorch_tpu.observability.report \
    "$out/shrink.jsonl"
python - "$out" <<'EOF'
import json, subprocess, sys
out = sys.argv[1]
js = json.loads(subprocess.check_output(
    [sys.executable, '-m',
     'distributed_kfac_pytorch_tpu.observability.report',
     f'{out}/shrink.jsonl', '--json']))
assert js['supervision']['failovers'] == 1, js['supervision']
print('report: supervision section carries the failover')
EOF
echo "supervisor smoke OK"
