#!/usr/bin/env bash
# Fast-tier randomized low-rank inverse smoke (r19): the knob end to
# end on CPU through the REAL LM entry point —
#   1. one tiny synthetic-corpus epoch with --inv-lowrank-rank engaged
#      on the model's FFN factor dims, under the full runtime
#      sanitizer (KFAC_SANITIZE=transfer,nan,retrace), metrics sink
#      on; assert the stream shows inverse firings, finite losses and
#      ZERO retrace events with the truncated path live;
#   2. observability-gate self-check over the stream (the CI plumbing
#      path, like overlap_smoke.sh's leg 2);
#   3. fail-closed leg: --inv-lowrank-rank at/above an engaged factor
#      dim must exit nonzero with an error NAMING the rank knob —
#      never a silent fallback to the exact path.
# The same contracts are pinned in tests/test_lowrank.py; this wrapper
# is the standalone/CI-pipeline form (see overlap_smoke.sh).
set -euo pipefail
cd "$(dirname "$0")/.."

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

run_lm() {  # $1 = leg name, extra args follow
    local leg="$1"; shift
    JAX_PLATFORMS=cpu KFAC_COMPILE_CACHE=0 KFAC_SYNTHETIC_LM=2048 \
    python examples/train_language_model.py \
        --arch transformer --emsize 64 --nlayers 1 --nheads 2 \
        --bptt 16 --batch-size 4 --epochs 1 --no-resume \
        --kfac-update-freq 4 \
        --log-dir "$out/logs-$leg" --checkpoint-dir "$out/ckpt-$leg" \
        "$@"
}

# Leg 1: low-rank engaged (FFN dims 256/257 >= threshold 128, rank 16)
# under the full sanitizer, metrics at interval 1.
KFAC_SANITIZE=transfer,nan,retrace \
run_lm lowrank \
    --inv-lowrank-rank 16 --inv-lowrank-dim-threshold 128 \
    --kfac-metrics "$out/lowrank.jsonl" --metrics-interval 1

python - "$out/lowrank.jsonl" <<'EOF'
import math
import sys

from distributed_kfac_pytorch_tpu.observability import sink as obs_sink

path = sys.argv[1]
records, _ = obs_sink.read_jsonl_tolerant(path)
steps = [r for r in records if r.get('kind') == 'step']
assert steps, 'no step records in the metrics stream'
fired = [r.get('fired') for r in steps]
assert 'inverse' in fired, fired        # truncated firings actually ran
assert all(math.isfinite(float(r['loss'])) for r in steps
           if 'loss' in r), 'non-finite loss with low-rank engaged'
retraces = [r for r in records if r.get('event') == 'retrace']
assert not retraces, retraces           # zero retraces, knob live
inv_firings = [r for r in steps
               if r.get('fired') == 'inverse']
assert inv_firings, fired
print(f'low-rank firing stages OK ({len(inv_firings)} firings over '
      f'{len(steps)} steps, zero retraces)')
EOF

# Leg 2: gate self-check (stream is gate-clean against itself).
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/lowrank.jsonl" --write-baseline "$out/B.json"
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/lowrank.jsonl" --baseline "$out/B.json" --allow-missing \
    --json > "$out/gate.json"
python - "$out/gate.json" <<'EOF'
import json, sys
v = json.load(open(sys.argv[1]))
assert v['pass'] is True, v
print('gate self-check OK')
EOF

# Leg 3: fail-closed — rank >= the engaged dim (FFN 256) must be a
# loud registration error naming the knob, not a silent exact-path
# fallback.
set +e
KFAC_SANITIZE=transfer,nan,retrace \
run_lm badrank \
    --inv-lowrank-rank 1024 --inv-lowrank-dim-threshold 128 \
    > "$out/badrank.log" 2>&1
rc=$?
set -e
if [ "$rc" -eq 0 ]; then
    echo 'FAIL: rank >= engaged dim did not error' >&2
    exit 1
fi
grep -q 'inv_lowrank_rank' "$out/badrank.log" || {
    echo 'FAIL: error does not name inv_lowrank_rank' >&2
    tail -5 "$out/badrank.log" >&2
    exit 1
}
echo "fail-closed rank leg OK (rc=$rc, error names the knob)"
echo 'lowrank_smoke: all legs OK'
