#!/usr/bin/env bash
# Fast-tier observability smoke (r7): 3 CPU steps of the CIFAR CLI with
# --kfac-metrics, then schema-validate the emitted JSONL via the report
# CLI (non-zero exit on invalid streams). The same check runs in the
# test suite as tests/test_observability.py::test_cifar_cli_metrics_smoke;
# this wrapper is the standalone/CI-pipeline form.
set -euo pipefail
cd "$(dirname "$0")/.."

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

JAX_PLATFORMS=cpu KFAC_COMPILE_CACHE=0 KFAC_SYNTHETIC_CIFAR=384 \
python examples/train_cifar10_resnet.py \
    --epochs 1 --model resnet20 \
    --batch-size 128 --val-batch-size 96 \
    --kfac-update-freq 1 --kfac-cov-update-freq 1 \
    --no-resume \
    --log-dir "$out/logs" --checkpoint-dir "$out/ckpt" \
    --kfac-metrics "$out/metrics.jsonl" \
    --metrics-interval 1 --health-action raise

python -m distributed_kfac_pytorch_tpu.observability.report \
    "$out/metrics.jsonl"
echo "metrics smoke OK"
