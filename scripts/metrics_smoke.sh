#!/usr/bin/env bash
# Fast-tier observability smoke (r7, extended r10): 3 CPU steps of the
# CIFAR CLI with --kfac-metrics + per-rank straggler shards + memory
# telemetry, then:
#   1. schema-validate the emitted JSONL via the report CLI (non-zero
#      exit on invalid streams) — the shard/memory sections ride along;
#   2. emit the machine-readable report (--json);
#   3. reduce the run to a gate baseline and re-gate the run against
#      itself (a clean self-baseline run must PASS).
# The same checks run in the suite as tests/test_observability.py::
# test_cifar_cli_metrics_smoke + tests/test_obs_perf.py; this wrapper
# is the standalone/CI-pipeline form.
set -euo pipefail
cd "$(dirname "$0")/.."

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

JAX_PLATFORMS=cpu KFAC_COMPILE_CACHE=0 KFAC_SYNTHETIC_CIFAR=384 \
python examples/train_cifar10_resnet.py \
    --epochs 1 --model resnet20 \
    --batch-size 128 --val-batch-size 96 \
    --kfac-update-freq 1 --kfac-cov-update-freq 1 \
    --no-resume \
    --log-dir "$out/logs" --checkpoint-dir "$out/ckpt" \
    --kfac-metrics "$out/metrics.jsonl" \
    --metrics-interval 1 --health-action raise \
    --straggler-shards --memory-interval 1

test -f "$out/metrics.jsonl.rank0" || {
    echo "missing straggler shard metrics.jsonl.rank0" >&2; exit 1; }

python -m distributed_kfac_pytorch_tpu.observability.report \
    "$out/metrics.jsonl"
python -m distributed_kfac_pytorch_tpu.observability.report \
    "$out/metrics.jsonl" --json > "$out/report.json"
python - "$out/report.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert r['n_steps'] == 3, r['n_steps']
assert r['stragglers'] and r['stragglers']['n_ranks'] == 1
assert r['memory'] and r['memory']['n_samples'] >= 1
assert r['compiles'], 'no compile events recorded'
assert not r['retraces'], r['retraces']
print('report --json OK')
EOF

# Gate: a clean run must pass against its own baseline (3 steps is too
# few for the percentile metrics to be meaningful, but the plumbing —
# reduce, write, compare, exit code — is exactly the CI path).
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/metrics.jsonl" --write-baseline "$out/BASELINE_OBS.json"
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/metrics.jsonl" --baseline "$out/BASELINE_OBS.json"
echo "metrics smoke OK"
