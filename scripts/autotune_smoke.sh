#!/usr/bin/env bash
# Fast-tier autotune smoke (r12): the closed loop end to end on CPU —
#   1. probe a 2-candidate space of the flagship LM probe workload and
#      commit a TUNED_flagship_lm.json artifact (+ its probe-stream
#      evidence at <out>.probe.jsonl);
#   2. reload the artifact through the real LM CLI via --tuned-config
#      (one tiny synthetic epoch) and check the metrics stream carries
#      exactly one autotune_apply event (report --json);
#   3. fail-closed leg: point the same CLI flag at a torn artifact and
#      check the run still completes on defaults with exactly one
#      autotune_fallback event;
#   4. gate self-check over the committed probe stream (reduce to a
#      baseline, re-gate against itself — the CI plumbing path; the
#      --json verdict must now carry the applied tolerances).
# The same checks run in the suite as tests/test_autotune.py; this
# wrapper is the standalone/CI-pipeline form (see metrics_smoke.sh).
set -euo pipefail
cd "$(dirname "$0")/.."

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

# 1. probe -> artifact (2 candidates keeps the compile bill smoke-sized)
JAX_PLATFORMS=cpu KFAC_COMPILE_CACHE=0 \
python -m distributed_kfac_pytorch_tpu.autotune \
    --workload flagship_lm --steps 6 --max-candidates 2 \
    --out "$out/TUNED_flagship_lm.json"
test -f "$out/TUNED_flagship_lm.json"
test -f "$out/TUNED_flagship_lm.json.probe.jsonl"

# 2. reload through the real LM CLI (tiny synthetic corpus: 32 steps)
run_lm() {  # $1 = tuned-config path, $2 = metrics path
    JAX_PLATFORMS=cpu KFAC_COMPILE_CACHE=0 KFAC_SYNTHETIC_LM=2048 \
    python examples/train_language_model.py \
        --arch transformer --emsize 32 --nlayers 1 --nheads 2 \
        --bptt 16 --batch-size 4 --epochs 1 \
        --kfac-update-freq 4 --no-resume \
        --log-dir "$out/logs" --checkpoint-dir "$out/ckpt-$(basename "$2" .jsonl)" \
        --kfac-metrics "$2" --metrics-interval 1 \
        --tuned-config "$1"
}
run_lm "$out/TUNED_flagship_lm.json" "$out/applied.jsonl"
python -m distributed_kfac_pytorch_tpu.observability.report \
    "$out/applied.jsonl" --json > "$out/applied.json"
python - "$out/applied.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
a = r['autotune']
assert a and a['applies'] == 1 and a['fallbacks'] == 0, a
print('tuned-config apply OK')
EOF

# 3. fail-closed: a torn artifact must fall back to defaults + 1 event
printf '{"format": "kfac-autotune' > "$out/torn.json"
run_lm "$out/torn.json" "$out/fellback.jsonl"
python -m distributed_kfac_pytorch_tpu.observability.report \
    "$out/fellback.jsonl" --json > "$out/fellback.json"
python - "$out/fellback.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
a = r['autotune']
assert a and a['fallbacks'] == 1 and a['applies'] == 0, a
print('fail-closed fallback OK')
EOF

# 4. gate self-check over the committed probe stream
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/TUNED_flagship_lm.json.probe.jsonl" \
    --write-baseline "$out/B.json"
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/TUNED_flagship_lm.json.probe.jsonl" \
    --baseline "$out/B.json" --allow-missing --json > "$out/gate.json"
python - "$out/gate.json" <<'EOF'
import json, sys
v = json.load(open(sys.argv[1]))
assert v['pass'] is True, v
assert 'tolerances' in v and 'step_p50_ms' in v['tolerances'], v
print('gate self-check OK')
EOF
echo "autotune smoke OK"
