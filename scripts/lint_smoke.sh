#!/usr/bin/env bash
# kfaclint CI smoke (r15), the standalone/CI-pipeline form of
# tests/test_lint.py + tests/test_surface.py — wired next to the
# observability gate in the verify flow:
#   1. lint the clean tree (exit 0 required; machine verdict pinned);
#   2. assert the seeded-violation fixtures FAIL (exit 1) — a linter
#      that cannot fail is decorative;
#   3. run a representative fast-tier engine module under
#      KFAC_SANITIZE=transfer,nan to prove the runtime sanitizer
#      gates hold on real train loops (the dynamic oracle), and that
#      the sanitizer catches a seeded hot-path host sync.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== 1. clean-tree lint =="
python -m distributed_kfac_pytorch_tpu.analysis.lint

python -m distributed_kfac_pytorch_tpu.analysis.lint --json \
    > /tmp/kfaclint.json
python - /tmp/kfaclint.json <<'EOF'
import json, sys
v = json.load(open(sys.argv[1]))
assert v['pass'] is True, v
assert set(v) == {'pass', 'n_files', 'n_findings', 'n_waived',
                  'findings', 'unused_waivers', 'skipped'}, sorted(v)
assert v['n_findings'] == 0
print(f"lint --json OK ({v['n_files']} files, "
      f"{v['n_waived']} documented waivers)")
EOF

echo "== 2. seeded violations must fail =="
for fixture in bad_host_sync bad_retrace bad_axis bad_dtype; do
    rc=0
    python -m distributed_kfac_pytorch_tpu.analysis.lint \
        --assume-hot "tests/fixtures/lint/$fixture.py" \
        > /dev/null 2>&1 || rc=$?
    # exactly 1 (violations found): rc 0 means the rule went blind,
    # rc 2 means the fixture itself is gone/unreadable
    if [ "$rc" -ne 1 ]; then
        echo "seeded violation $fixture.py: expected lint rc 1," \
             "got $rc" >&2
        exit 1
    fi
    echo "  $fixture.py fails as expected (rc 1)"
done
# waived violations must pass (the waiver syntax is load-bearing)
python -m distributed_kfac_pytorch_tpu.analysis.lint \
    --assume-hot tests/fixtures/lint/waived_ok.py > /dev/null
echo "  waived_ok.py passes as expected"

echo "== 3. sanitizer mode over a real engine module =="
JAX_PLATFORMS=cpu KFAC_SANITIZE=transfer,nan \
python -m pytest tests/test_static_cadence.py -q -m 'not slow' \
    -p no:cacheprovider

# ... and the sanitizer must CATCH a violation (load-bearing, not
# decorative): a hot-path device_get inside a warm step dispatch.
JAX_PLATFORMS=cpu KFAC_SANITIZE=transfer python - <<'EOF'
import jax, jax.numpy as jnp, numpy as np
from distributed_kfac_pytorch_tpu.analysis import sanitize
from distributed_kfac_pytorch_tpu.training import engine

@jax.jit
def mul(p, b):
    return p * 1.001, jnp.mean(b)

def dirty(params, opt_state, kstate, extra_vars, batch, hyper):
    params, loss = mul(params, batch)
    jax.device_get(loss)  # seeded hot-path host sync
    return params, opt_state, kstate, extra_vars, {'loss': loss}

state = engine.TrainState(params=jnp.ones(()), opt_state=None,
                          kfac_state=None, extra_vars={})
try:
    engine.train_epoch(dirty, state, [np.ones(4, np.float32)] * 3,
                       {}, static_cadence=None)
except sanitize.SanitizerError as e:
    print('sanitizer caught the seeded violation OK')
else:
    raise SystemExit('sanitizer MISSED the seeded hot-path host sync')
EOF

echo "lint smoke OK"
