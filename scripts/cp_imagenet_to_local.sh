#!/bin/bash
# Stage the ImageNet tree to node-local fast storage before training.
#
# Reference parity: scripts/cp_imagenet_to_temp.sh (untars ImageNet to
# /tmp on every node so the input pipeline reads local disk instead of
# the shared filesystem). TPU-VM equivalent: stage to the local SSD (or
# a ramdisk) on every worker; the tf.data pipeline in
# training/datasets.py then reads local JPEGs.
#
# Usage: ./scripts/cp_imagenet_to_local.sh /shared/imagenet /tmp/imagenet
set -euo pipefail

SRC=${1:?source imagenet dir (train/ + val/)}
DST=${2:-/tmp/imagenet}

mkdir -p "${DST}"
for split in train val; do
  if [ -f "${SRC}/${split}.tar" ]; then
    echo "untarring ${split}.tar -> ${DST}/${split}"
    mkdir -p "${DST}/${split}"
    tar -xf "${SRC}/${split}.tar" -C "${DST}/${split}"
  elif [ -d "${SRC}/${split}" ]; then
    echo "copying ${split}/ -> ${DST}/${split}"
    cp -r --no-clobber "${SRC}/${split}" "${DST}/"
  else
    echo "missing ${SRC}/${split}(.tar)" >&2
    exit 1
  fi
done
echo "staged to ${DST}; pass --data-dir ${DST}"
