#!/usr/bin/env bash
# r14 artifact generation (CPU provenance — see PERF.md r14): the
# compute/communication-overlap evidence set. Rerun on v5e before
# promoting either knob (decision rule: PERF.md r14).
#   FLAGSHIP_LM_r14_STALENESS.jsonl  eager-vs-stale LM loss curves
#   CONVERGENCE_R14_STALENESS_GN_S{0,1}.json  GN-conv A/B (S1 = both
#       knobs on: inv_staleness=1 + deferred reduce)
#   BENCH_r14_OVERLAP.json  straggler-shard before/after: per-leg
#       step-time distribution + comm-wait-by-stage attribution from
#       an 8-virtual-device run of the real LM CLI
set -euo pipefail
cd "$(dirname "$0")/.."

# 1) LM staleness convergence A/B (identical hyperparameters; the
#    'stale' leg runs inv_staleness=1 + deferred_factor_reduction).
JAX_PLATFORMS=cpu python benchmarks/flagship_lm.py --staleness-ab \
    --ladder 128 256 --ab-steps 60 --ab-seq 64 --ab-batch 8 \
    --ab-vocab 512 --ab-layers 2 --ab-f 5 --ab-i 20 \
    > FLAGSHIP_LM_r14_STALENESS.jsonl.tmp
mv FLAGSHIP_LM_r14_STALENESS.jsonl.tmp FLAGSHIP_LM_r14_STALENESS.jsonl

# 2) GN-conv convergence A/B (the r4/r9 study's control model).
python benchmarks/convergence.py --model resnet20gn --epochs 8 \
    --batch-size 128 --synthetic-size 2048 --kfac-update-freq 10 \
    --only kfac --platform cpu \
    --out CONVERGENCE_R14_STALENESS_GN_S0.json
python benchmarks/convergence.py --model resnet20gn --epochs 8 \
    --batch-size 128 --synthetic-size 2048 --kfac-update-freq 10 \
    --only kfac --inv-staleness 1 --deferred-factor-reduction \
    --platform cpu --out CONVERGENCE_R14_STALENESS_GN_S1.json

# 3) Straggler-shard before/after on the 8-virtual-device mesh: the
#    factor-step barrier wait and firing-step spike the overlap moves.
out="$(mktemp -d)"; trap 'rm -rf "$out"' EXIT
run_leg() {  # $1 = leg name, extra CLI args follow
    local leg="$1"; shift
    JAX_PLATFORMS=cpu KFAC_COMPILE_CACHE=0 KFAC_SYNTHETIC_LM=4096 \
    XLA_FLAGS="--xla_force_host_platform_device_count=8 ${XLA_FLAGS:-}" \
    python - "$leg" "$out" "$@" <<'EOF'
import sys
from distributed_kfac_pytorch_tpu.utils import (
    raise_cpu_collective_timeouts,
)
raise_cpu_collective_timeouts()
from examples import train_language_model as lm

leg, out, *extra = sys.argv[1:]
rc = lm.main([
    '--arch', 'transformer', '--emsize', '64', '--nlayers', '1',
    '--nheads', '2', '--bptt', '16', '--batch-size', '8',
    '--epochs', '2', '--no-resume', '--kfac-update-freq', '8',
    '--kfac-cov-update-freq', '2',
    '--log-dir', f'{out}/logs-{leg}',
    '--checkpoint-dir', f'{out}/ckpt-{leg}',
    '--kfac-metrics', f'{out}/{leg}.jsonl', '--metrics-interval', '1',
    '--straggler-shards', *extra])
sys.exit(rc)
EOF
}
run_leg eager
run_leg overlap --inv-pipeline-chunks 2 \
    --deferred-factor-reduction --inv-staleness 1

python - "$out" <<'EOF'
import json, subprocess, sys

out = sys.argv[1]
legs = {}
for leg in ('eager', 'overlap'):
    rep = json.loads(subprocess.run(
        [sys.executable, '-m',
         'distributed_kfac_pytorch_tpu.observability.report',
         f'{out}/{leg}.jsonl', '--json'],
        capture_output=True, text=True, check=True,
        env={**__import__('os').environ,
             'JAX_PLATFORMS': 'cpu'}).stdout)
    st = rep['step_time']
    sg = rep['stragglers'] or {}
    legs[leg] = {
        'n_steps': st['n_steps'],
        'p50_ms': st['p50_ms'], 'p95_ms': st['p95_ms'],
        'p99_ms': st['p99_ms'], 'max_ms': st['max_ms'],
        'max_over_median': st['max_over_median'],
        'outlier_stages': {k: v for k, v in st['stages'].items()
                           if v['outliers']},
        'wait_by_stage': sg.get('wait_by_stage'),
        'mean_skew_ms': sg.get('mean_skew_ms'),
        'retraces': len(rep['retraces']),
    }
obj = {
    'bench': 'r14_overlap_straggler_ab',
    'provenance': 'CPU, 8 virtual devices on a shared host — wait/'
                  'skew magnitudes are NOT v5e numbers (PERF.md r14); '
                  'the comparison is the factor-step wait share and '
                  'the firing-step spike, eager vs overlap',
    'workload': 'transformer_lm d64 L1 bptt16 b8, f1/i8, 2 epochs '
                '(64 steps), COMM_OPT 8-dev virtual mesh',
    'overlap_flags': ['--inv-pipeline-chunks 2',
                      '--deferred-factor-reduction',
                      '--inv-staleness 1'],
    'legs': legs,
}
with open('BENCH_r14_OVERLAP.json', 'w') as f:
    json.dump(obj, f, indent=1, sort_keys=True)
    f.write('\n')
print(json.dumps(obj['legs'], indent=1, sort_keys=True))
EOF
echo "r14 artifacts written: FLAGSHIP_LM_r14_STALENESS.jsonl" \
     "CONVERGENCE_R14_STALENESS_GN_S{0,1}.json BENCH_r14_OVERLAP.json"
