#!/usr/bin/env bash
# Fleet-scheduler smoke (r18): the training-as-a-service layer proven
# end-to-end on CPU through the real CIFAR CLI — a 3-job pack with an
# urgent admission (preempt-by-shrink then regrow, through the per-job
# capacity files and the elastic resume), a job-kill + pool-loss chaos
# leg (recovery inside the job's own supervisor budget, then a
# pool-capacity shrink), a crash-loop-isolation leg (the looping job
# quarantined with its diagnostic while its pool-mate completes), and
# the observability round-trip (report --json fleet key-set pinned +
# the gate's fleet_quarantines metric). The fast jax-free matrix rides
# in tests/test_fleet.py; this wrapper is the standalone/CI form.
set -euo pipefail
cd "$(dirname "$0")/.."

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

export JAX_PLATFORMS=cpu KFAC_SYNTHETIC_CIFAR=384
fleet=(python -m distributed_kfac_pytorch_tpu.fleet)
# Per-job supervisor knobs: hang timeout above the lease-silent
# eval/checkpoint/compile tail, zero backoff for speed.
fleet_args=(--poll 0.5 --aging-secs 5 --hang-timeout 600
            --startup-grace 600 --job-poll 0.5 --drain-grace 300
            --backoff 0 --crash-loop-after 2 --deadline 1800)

cifar_argv() {  # cifar_argv <leg> <job> <epochs> -> JSON argv tail
    python - "$@" <<'EOF'
import json, sys
leg, job, epochs = sys.argv[1:4]
print(json.dumps([
    'python', 'examples/train_cifar10_resnet.py',
    '--epochs', epochs, '--model', 'resnet20',
    '--batch-size', '128', '--val-batch-size', '96',
    '--kfac-update-freq', '1', '--kfac-cov-update-freq', '1',
    '--checkpoint-steps', '1', '--metrics-interval', '1',
    '--log-dir', f'{leg}/logs-{job}',
    '--checkpoint-dir', f'{leg}/ckpt-{job}']))
EOF
}

echo "== leg 1: 3-job pack — urgent admission shrinks the steady =="
echo "==        job 2 -> 1 and regrows it after (capacity channel) =="
mkdir -p "$out/leg1"
python - "$out" "$(cifar_argv "$out/leg1" steady 10)" \
               "$(cifar_argv "$out/leg1" mate 1)" \
               "$(cifar_argv "$out/leg1" urgent 1)" <<'EOF'
import json, sys
out, steady, mate, urgent = sys.argv[1:5]
jobs = {'jobs': [
    {'name': 'steady', 'argv': json.loads(steady), 'priority': 1,
     'min_devices': 1, 'max_devices': 2},
    {'name': 'mate', 'argv': json.loads(mate), 'priority': 2,
     'min_devices': 1, 'max_devices': 1},
    {'name': 'urgent', 'argv': json.loads(urgent), 'priority': 9,
     'min_devices': 2, 'max_devices': 2, 'after_s': 40},
]}
json.dump(jobs, open(f'{out}/leg1/jobs.json', 'w'), indent=1)
EOF
env KFAC_COMPILE_CACHE=0 \
"${fleet[@]}" "$out/leg1/jobs.json" --pool-devices 4 \
    --workdir "$out/leg1/fleet" "${fleet_args[@]}"

python - "$out/leg1" <<'EOF'
import sys
from distributed_kfac_pytorch_tpu.observability import sink

leg = sys.argv[1]
ev = [(r['event'], r['data'])
      for r in sink.read_jsonl(f'{leg}/fleet/fleet.jsonl')
      if r['kind'] == 'event']
kinds = [k for k, _ in ev]
assert kinds.count('fleet_admit') == 3, kinds
assert kinds.count('fleet_complete') == 3, kinds
pre = next(d for k, d in ev if k == 'fleet_preempt')
assert pre['job'] == 'steady', pre
assert (pre['from_devices'], pre['to_devices']) == (2, 1), pre
assert pre['reason'] == 'admission', pre
re = next(d for k, d in ev if k == 'fleet_regrow')
assert (re['job'], re['from_devices'], re['to_devices']) \
    == ('steady', 1, 2), re
# The urgent admission ordering: preempt before urgent's admit,
# urgent's completion before the regrow.
assert kinds.index('fleet_preempt') \
    < kinds.index('fleet_complete'), kinds
side = [r['event'] for r in sink.read_jsonl(
    f'{leg}/fleet/jobs/steady/metrics.jsonl.supervisor')
    if r['kind'] == 'event']
assert 'supervisor_failover' in side and 'supervisor_growback' in side, side
print('leg 1: urgent admission shrank steady 2->1 and regrew it, '
      'all 3 jobs completed')
EOF

echo "== leg 2: job-kill + pool-loss chaos — supervised recovery, =="
echo "==        then a pool shrink 2 -> 1 =="
mkdir -p "$out/leg2"
python - "$out" "$(cifar_argv "$out/leg2" a 6)" <<'EOF'
import json, sys
out, a = sys.argv[1:3]
jobs = [{'name': 'a', 'argv': json.loads(a),
         'min_devices': 1, 'max_devices': 2}]
json.dump(jobs, open(f'{out}/leg2/jobs.json', 'w'), indent=1)
EOF
env KFAC_COMPILE_CACHE=0 KFAC_FLEET_CHAOS='job-kill@30,pool-loss@160->1' \
"${fleet[@]}" "$out/leg2/jobs.json" --pool-devices 2 \
    --workdir "$out/leg2/fleet" "${fleet_args[@]}"

python - "$out/leg2" <<'EOF'
import sys
from distributed_kfac_pytorch_tpu.observability import sink

leg = sys.argv[1]
ev = [(r['event'], r['data'])
      for r in sink.read_jsonl(f'{leg}/fleet/fleet.jsonl')
      if r['kind'] == 'event']
kinds = [k for k, _ in ev]
assert 'fleet_quarantine' not in kinds, ev
done = next(d for k, d in ev if k == 'fleet_complete')
assert done['restarts'] >= 1, done  # the kill burned one relaunch
pre = next(d for k, d in ev if k == 'fleet_preempt')
assert pre['reason'] == 'pool-loss', pre
assert (pre['from_devices'], pre['to_devices']) == (2, 1), pre
side = [(r['event'], r['data']) for r in sink.read_jsonl(
    f'{leg}/fleet/jobs/a/metrics.jsonl.supervisor')
    if r['kind'] == 'event']
assert any(k == 'supervisor_restart' and d['reason'] == 'crash'
           for k, d in side), side
assert any(k == 'supervisor_failover' and d['to_devices'] == 1
           for k, d in side), side
print('leg 2: job-kill recovered inside the job budget; pool-loss '
      'shrank the world 2->1 through the elastic resume')
EOF

echo "== leg 3: crash-loop isolation — the looping job quarantined =="
echo "==        (exit 77 + diagnostic), its pool-mate completes =="
mkdir -p "$out/leg3"
python - "$out" "$(cifar_argv "$out/leg3" loop 1)" \
               "$(cifar_argv "$out/leg3" ok 1)" <<'EOF'
import json, sys
out, loop, ok = sys.argv[1:4]
jobs = [
    {'name': 'loop', 'argv': json.loads(loop), 'priority': 5,
     'max_restarts': 5, 'keep_faults': True,
     'env': {'KFAC_CHAOS': 'crash@2'}},
    {'name': 'ok', 'argv': json.loads(ok), 'priority': 1},
]
json.dump(jobs, open(f'{out}/leg3/jobs.json', 'w'), indent=1)
EOF
set +e
env KFAC_COMPILE_CACHE="$out/cache" \
"${fleet[@]}" "$out/leg3/jobs.json" --pool-devices 1 \
    --workdir "$out/leg3/fleet" "${fleet_args[@]}"
rc=$?
set -e
[ "$rc" -eq 1 ] || { echo "expected fleet exit 1 (quarantine), got $rc"; exit 1; }

python - "$out/leg3" <<'EOF'
import json, sys
from distributed_kfac_pytorch_tpu.observability import sink

leg = sys.argv[1]
ev = [(r['event'], r['data'])
      for r in sink.read_jsonl(f'{leg}/fleet/fleet.jsonl')
      if r['kind'] == 'event']
q = next(d for k, d in ev if k == 'fleet_quarantine')
assert q['job'] == 'loop' and q['rc'] == 77, q
assert q['reason'] == 'crash_loop', q
diag = json.load(open(q['diagnostic']))
assert diag['history'], diag
done = next(d for k, d in ev if k == 'fleet_complete')
assert done['job'] == 'ok', done
print('leg 3: crash-looping job quarantined with its diagnostic, '
      'pool-mate completed')
EOF

echo "== report --json fleet key-set pinned + gate round-trip =="
python -m distributed_kfac_pytorch_tpu.observability.report \
    "$out/leg3/fleet/fleet.jsonl"
python - "$out" <<'EOF'
import json, subprocess, sys
out = sys.argv[1]
js = json.loads(subprocess.check_output(
    [sys.executable, '-m',
     'distributed_kfac_pytorch_tpu.observability.report',
     f'{out}/leg3/fleet/fleet.jsonl', '--json']))
fleet = js['fleet']
assert fleet['quarantines'] == 1 and fleet['completes'] == 1, fleet
rows = fleet['jobs']
assert set(rows) == {'loop', 'ok'}, rows
for row in rows.values():
    assert set(row) == {'outcome', 'rc', 'devices', 'queue_wait_s',
                        'run_s', 'restarts', 'preemptions', 'gate',
                        'reason'}, row
print('report: fleet key + per-job SLO rows pinned')
EOF
# Gate: a clean fleet stream baselines fleet_quarantines=0; the
# quarantined leg must breach it.
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/leg1/fleet/fleet.jsonl" --write-baseline "$out/base.json" \
    --allow-missing
set +e
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/leg3/fleet/fleet.jsonl" --baseline "$out/base.json" \
    --allow-missing --no-anomaly --json > "$out/gate.json"
rc=$?
set -e
[ "$rc" -eq 1 ] || { echo "expected gate breach exit 1, got $rc"; exit 1; }
python - "$out" <<'EOF'
import json, sys
v = json.load(open(f'{sys.argv[1]}/gate.json'))
assert v['current']['fleet_quarantines'] == 1, v['current']
assert any(b['metric'] == 'fleet_quarantines' for b in v['breaches']), v
print('gate: fleet_quarantines round-trips and gates the quarantine')
EOF
echo "fleet smoke OK"
