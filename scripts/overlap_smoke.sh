#!/usr/bin/env bash
# Fast-tier compute/communication-overlap smoke (r14): both knobs end
# to end on CPU through the REAL LM entry point —
#   1. one tiny synthetic-corpus epoch with --deferred-factor-reduction
#      and --inv-staleness 1 (chunked, k=2), straggler shards on with
#      the sampled probe (--straggler-sample-every 2), metrics sink on;
#   2. assert the stream shows the r14 schedule (fired='reduce' window
#      heads, chunk firings, ZERO retrace events) and that the merged
#      report carries the comm-wait-by-stage attribution from the
#      sparse (sampled) shard;
#   3. observability-gate self-check over the stream (the CI plumbing
#      path, like autotune_smoke.sh's leg 4);
#   4. fail-closed composition with --tuned-config: an artifact whose
#      tuned knobs violate the staleness window constraint against the
#      CLI's live cadence must fall back to flag defaults with exactly
#      one autotune_fallback event — never half-apply.
# The same contracts are pinned in tests/test_overlap.py; this wrapper
# is the standalone/CI-pipeline form (see sharing_smoke.sh).
set -euo pipefail
cd "$(dirname "$0")/.."

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

run_lm() {  # $1 = leg name, $2 = metrics path, extra args follow
    local leg="$1" metrics="$2"; shift 2
    JAX_PLATFORMS=cpu KFAC_COMPILE_CACHE=0 KFAC_SYNTHETIC_LM=2048 \
    python examples/train_language_model.py \
        --arch transformer --emsize 64 --nlayers 1 --nheads 2 \
        --bptt 16 --batch-size 4 --epochs 1 --no-resume \
        --kfac-update-freq 8 --inv-pipeline-chunks 2 \
        --deferred-factor-reduction --inv-staleness 1 \
        --log-dir "$out/logs-$leg" --checkpoint-dir "$out/ckpt-$leg" \
        --kfac-metrics "$metrics" --metrics-interval 1 "$@"
}

# Leg 1: both knobs + sampled straggler shards.
run_lm overlap "$out/overlap.jsonl" \
    --straggler-shards --straggler-sample-every 2

python - "$out/overlap.jsonl" <<'EOF'
import sys
from distributed_kfac_pytorch_tpu.observability import sink as obs_sink
from distributed_kfac_pytorch_tpu.observability import (
    stragglers as obs_stragglers,
)

path = sys.argv[1]
records, _ = obs_sink.read_jsonl_tolerant(path)
fired = [r.get('fired') for r in records if r.get('kind') == 'step']
assert 'reduce' in fired, fired  # deferred window-boundary reduce ran
assert any(f and f.startswith('chunk') for f in fired), fired
retraces = [r for r in records if r.get('event') == 'retrace']
assert not retraces, retraces    # zero retraces with both knobs on

shards, torn, errors = obs_stragglers.merge_shards(path)
assert shards and not errors, (shards.keys(), errors)
summary = obs_stragglers.straggler_summary(shards)
wbs = summary['wait_by_stage']
assert wbs, summary              # sampled probe still attributed
n_steps = sum(1 for r in shards[0] if r.get('kind') == 'step')
n_waits = sum(v['n'] for v in wbs.values())
assert 0 < n_waits <= (n_steps + 1) // 2 + 1, (n_waits, n_steps)
print('overlap schedule + sampled wait attribution OK '
      f'(waits on {n_waits}/{n_steps} steps)')
EOF

# Leg 2: gate self-check (stream is gate-clean against itself).
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/overlap.jsonl" --write-baseline "$out/B.json"
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/overlap.jsonl" --baseline "$out/B.json" --allow-missing \
    --json > "$out/gate.json"
python - "$out/gate.json" <<'EOF'
import json, sys
v = json.load(open(sys.argv[1]))
assert v['pass'] is True, v
print('gate self-check OK')
EOF

# Leg 3: fail-closed --tuned-config composition. The artifact tunes
# inv_staleness=1 with inv_pipeline_chunks=8 — invalid against the
# CLI's --kfac-update-freq 8 window (stride < 2), so the merge must
# fall back to the flag defaults with one autotune_fallback event.
python - "$out/TUNED_bad.json" <<'EOF'
import sys
from distributed_kfac_pytorch_tpu.autotune import driver
import jax
driver.write_tuned(sys.argv[1], {
    'workload': 'overlap_smoke',
    'platform': jax.default_backend(),
    'topology': {'topo_devices': jax.device_count(),
                 'topo_processes': jax.process_count(),
                 'topo_seq': 1},
    'best': {'inv_staleness': 1, 'inv_pipeline_chunks': 8},
    'best_score': 1.0, 'candidates': []})
EOF
run_lm fallback "$out/fallback.jsonl" --tuned-config "$out/TUNED_bad.json"

python - "$out/fallback.jsonl" <<'EOF'
import sys
from distributed_kfac_pytorch_tpu.observability import sink as obs_sink

records, _ = obs_sink.read_jsonl_tolerant(sys.argv[1])
falls = [r for r in records if r.get('event') == 'autotune_fallback']
applies = [r for r in records if r.get('event') == 'autotune_apply']
assert len(falls) == 1 and not applies, (falls, applies)
assert falls[0]['data']['reason'] == 'invalid_merge', falls[0]
steps = [r for r in records if r.get('kind') == 'step']
assert steps, 'fallback run still trained'
print('tuned-config fail-closed OK')
EOF

echo "overlap smoke OK"
