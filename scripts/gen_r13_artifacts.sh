#!/usr/bin/env bash
# r13 artifact generation (CPU provenance — see PERF.md r13): the
# d512->d2048 expand/reduce ladder evidence. Rung sizes shrink with d
# so the single-core CPU run stays bounded; every JSONL row records
# its own config, so mixed-rung files are self-describing. Rerun on
# v5e with the full sizes before promoting a default (decision rule:
# PERF.md r13).
set -euo pipefail
cd "$(dirname "$0")/.."

Q=FLAGSHIP_LM_r13_APPROX.jsonl
C=BENCH_r13_APPROX_COST.jsonl
: > "$Q.tmp"; : > "$C.tmp"

# Quality ladder: expand vs reduce vs SGD loss curves per rung.
JAX_PLATFORMS=cpu python benchmarks/flagship_lm.py --approx-ab \
    --ladder 512 --ab-steps 48 --ab-seq 64 --ab-batch 8 \
    --ab-vocab 512 --ab-layers 2 >> "$Q.tmp"
JAX_PLATFORMS=cpu python benchmarks/flagship_lm.py --approx-ab \
    --ladder 1024 --ab-steps 32 --ab-seq 64 --ab-batch 4 \
    --ab-vocab 512 --ab-layers 2 >> "$Q.tmp"
JAX_PLATFORMS=cpu python benchmarks/flagship_lm.py --approx-ab \
    --ladder 2048 --ab-steps 12 --ab-seq 32 --ab-batch 2 \
    --ab-vocab 256 --ab-layers 1 --ab-f 2 --ab-i 12 >> "$Q.tmp"

# Factor-update cost rows: the ~T x reduce claim, per rung.
JAX_PLATFORMS=cpu python benchmarks/step_breakdown.py --lm-approx \
    --lm-d 512 1024 --lm-seq 128 --lm-batch 4 --lm-vocab 512 \
    --iters 4 >> "$C.tmp"
JAX_PLATFORMS=cpu python benchmarks/step_breakdown.py --lm-approx \
    --lm-d 2048 --lm-seq 64 --lm-batch 2 --lm-vocab 256 \
    --iters 2 >> "$C.tmp"

mv "$Q.tmp" "$Q"; mv "$C.tmp" "$C"
echo "r13 artifacts written: $Q $C"
