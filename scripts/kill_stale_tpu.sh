#!/bin/bash
# Kill stale training processes holding the TPU on every pod worker.
#
# Reference parity: scripts/kill_python_process.sh (clears hung CUDA
# processes cluster-wide). A crashed JAX process can keep libtpu locked
# (/tmp/libtpu_lockfile), making the next launch fail with "TPU in use".
#
# Usage: ./scripts/kill_stale_tpu.sh <tpu-name> <zone>
set -euo pipefail

TPU_NAME=${1:?tpu name}
ZONE=${2:?zone}

gcloud compute tpus tpu-vm ssh "${TPU_NAME}" --zone "${ZONE}" \
  --worker=all \
  --command='pkill -9 -f "[p]ython.*train_" || true; \
             rm -f /tmp/libtpu_lockfile || true'
