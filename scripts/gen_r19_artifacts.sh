#!/usr/bin/env bash
# r19 artifact generation (CPU provenance — see PERF.md r19): the
# randomized low-rank inverse evidence set. Rerun on v5e before
# promoting the knob (decision rule: PERF.md r19).
#   BENCH_r19_LOWRANK.json          firing_spread exact-vs-lowrank
#       legs on the CPU-scaled config-4 d512/L8 workload (window
#       inverse cost + spike ratio)
#   FLAGSHIP_LM_r19_LOWRANK.jsonl   per-rung loss curves, exact vs
#       rank-64 engaged on the rung's FFN dims (threshold 2*d)
#   step_breakdown --lm-lowrank     engaged-bucket per-firing cost
#       rows (exact eigh vs Cholesky vs warm low-rank) — printed, the
#       eigh_over_lowrank number is quoted in PERF.md r19
set -euo pipefail
cd "$(dirname "$0")/.."

# 1) Window-level firing-spread A/B (monolithic k=1 both legs).
JAX_PLATFORMS=cpu python benchmarks/firing_spread.py --lowrank \
    --windows 3 --inv-update-freq 8 \
    --lowrank-rank 64 --lowrank-dim-threshold 1024 \
    --out BENCH_r19_LOWRANK.json

# 2) LM convergence ladder (identical hyperparameters per rung).
JAX_PLATFORMS=cpu python benchmarks/flagship_lm.py --lowrank-ab \
    --ladder 256 512 --ab-steps 60 --ab-lowrank-rank 64 \
    > FLAGSHIP_LM_r19_LOWRANK.jsonl.tmp
mv FLAGSHIP_LM_r19_LOWRANK.jsonl.tmp FLAGSHIP_LM_r19_LOWRANK.jsonl

# 3) Engaged-bucket decomposition cost (quoted in PERF.md r19).
JAX_PLATFORMS=cpu python benchmarks/step_breakdown.py --lm-lowrank \
    --lm-d 512 1024 --lowrank-rank 64

echo "r19 artifacts written: BENCH_r19_LOWRANK.json" \
     "FLAGSHIP_LM_r19_LOWRANK.jsonl"
