#!/usr/bin/env bash
# Fast-tier sharing smoke (r13): the expand/reduce subsystem end to end
# on CPU through the REAL LM entry point —
#   1. one tiny synthetic-corpus epoch per approximation (d64
#      transformer, --kfac-approx expand | reduce) with the metrics
#      sink on;
#   2. assert the per-layer resolved approx landed in the stream's
#      kind='meta' records (the registry provenance emit_layer_meta
#      appends after registration) — expand everywhere on the expand
#      leg, reduce on every attention/MLP Dense (+ tied embedding) on
#      the reduce leg;
#   3. observability-gate self-check over the reduce leg's stream
#      (write a baseline from it, re-gate against itself) — the CI
#      plumbing path, like autotune_smoke.sh's leg 4.
# The same checks run in the suite as tests/test_sharing.py; this
# wrapper is the standalone/CI-pipeline form (see autotune_smoke.sh).
set -euo pipefail
cd "$(dirname "$0")/.."

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

run_lm() {  # $1 = approx, $2 = metrics path
    JAX_PLATFORMS=cpu KFAC_COMPILE_CACHE=0 KFAC_SYNTHETIC_LM=2048 \
    python examples/train_language_model.py \
        --arch transformer --emsize 64 --nlayers 1 --nheads 2 \
        --bptt 16 --batch-size 4 --epochs 1 --tied \
        --kfac-update-freq 4 --no-resume \
        --log-dir "$out/logs-$1" --checkpoint-dir "$out/ckpt-$1" \
        --kfac-metrics "$2" --metrics-interval 1 \
        --kfac-approx "$1"
}

run_lm expand "$out/expand.jsonl"
run_lm reduce "$out/reduce.jsonl"

python - "$out/expand.jsonl" "$out/reduce.jsonl" <<'EOF'
import sys
from distributed_kfac_pytorch_tpu.observability import sink as obs_sink

def layer_meta(path):
    records, _ = obs_sink.read_jsonl_tolerant(path)
    for r in records:
        if r.get('kind') == 'meta' and 'kfac_approx' in r.get('meta', {}):
            return r['meta']
    raise SystemExit(f'{path}: no kfac_approx meta record')

m = layer_meta(sys.argv[1])
assert m['kfac_approx_setting'] == 'expand', m
assert set(m['kfac_approx'].values()) == {'expand'}, m['kfac_approx']

m = layer_meta(sys.argv[2])
assert m['kfac_approx_setting'] == 'reduce', m
per = m['kfac_approx']
assert per['block0/attn/q_proj'] == 'reduce', per
assert per['block0/mlp_in'] == 'reduce', per
assert per['embed'] == 'expand+tied', per
assert m['tied_embeddings'] is True, m
print('per-layer approx meta OK')
EOF

# Gate self-check: the reduce leg's stream gates green against itself.
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/reduce.jsonl" --write-baseline "$out/B.json"
python -m distributed_kfac_pytorch_tpu.observability.gate \
    "$out/reduce.jsonl" --baseline "$out/B.json" --allow-missing \
    --json > "$out/gate.json"
python - "$out/gate.json" <<'EOF'
import json, sys
v = json.load(open(sys.argv[1]))
assert v['pass'] is True, v
print('gate self-check OK')
EOF
echo "sharing smoke OK"
