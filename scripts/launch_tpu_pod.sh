#!/bin/bash
# Launch a training example on every worker of a Cloud TPU pod slice.
#
# Reference L5 parity: scripts/launch_node_torch_imagenet.sh bridges
# mpiexec + per-node torch.distributed.launch with MVAPICH2-GDR env; on
# TPU the pod runtime already provides rendezvous, so launch is one ssh
# fan-out and jax.distributed.initialize() inside the script
# (distributed_kfac_pytorch_tpu/launch.py) picks up the topology.
#
# Usage:
#   ./scripts/launch_tpu_pod.sh <tpu-name> <zone> examples/train_imagenet_resnet.py [args...]
set -euo pipefail

TPU_NAME=${1:?tpu name}
ZONE=${2:?zone}
shift 2
SCRIPT=${1:?training script}
shift || true

gcloud compute tpus tpu-vm ssh "${TPU_NAME}" --zone "${ZONE}" \
  --worker=all \
  --command="cd ~/distributed_kfac_pytorch_tpu && python ${SCRIPT} $*"
