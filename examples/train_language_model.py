"""Language-model training with distributed K-FAC: LSTM or Transformer.

Working TPU-native counterpart of the reference's WIP LM entry point
(examples/torch_language_model.py — broken as shipped: SURVEY.md §8 notes
the lr and factory-unpacking bugs at :253,:277). Two architectures:

- ``--arch lstm``: the K-FAC-friendly LSTM LM (reference rnn_utils/lstm.py
  + kfac/modules/lstm.py), BPTT windows (``--bptt 35``,
  torch_language_model.py:52), K-FAC on the LSTM-cell Linears with
  embedding/decoder skipped by default (torch_language_model.py:102-104).
  Hidden state is reset per window (the reference carries it detached;
  with windows shuffled per epoch the difference is negligible).
- ``--arch transformer``: decoder-only Transformer with Linear-layer
  K-FAC on every projection (BASELINE config 4), and optional
  ``--seq-parallel N`` ring-attention context parallelism over the mesh
  (no reference analogue — SURVEY.md §5: long-context machinery absent).

Data: whitespace-tokenized train.txt/valid.txt under --data-dir
(PTB/WikiText layout), else a synthetic Markov corpus (offline default).
"""

from __future__ import annotations

import argparse
import math
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from jax.sharding import PartitionSpec as P

from distributed_kfac_pytorch_tpu import autotune
from distributed_kfac_pytorch_tpu import elastic as elastic_lib
from distributed_kfac_pytorch_tpu import fp16 as fp16_lib
from distributed_kfac_pytorch_tpu import launch
from distributed_kfac_pytorch_tpu import observability as obs
from distributed_kfac_pytorch_tpu import resilience as resil
from distributed_kfac_pytorch_tpu import multislice
from distributed_kfac_pytorch_tpu.models import lstm_lm, transformer_lm
from distributed_kfac_pytorch_tpu.parallel import distributed as D
from distributed_kfac_pytorch_tpu.parallel import sequence as seq
from distributed_kfac_pytorch_tpu.training import (
    checkpoint as ckpt_lib,
    datasets,
    engine,
    optimizers,
)

from distributed_kfac_pytorch_tpu.utils import enable_compilation_cache

enable_compilation_cache()  # persistent compile cache (KFAC_COMPILE_CACHE=0 disables)


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description='LM + distributed K-FAC (TPU-native)')
    p.add_argument('--data-dir', default=None,
                   help='dir with train.txt/valid.txt (synthetic if '
                        'absent)')
    p.add_argument('--log-dir', default='./logs/lm')
    p.add_argument('--checkpoint-dir', default='./checkpoints/lm')
    p.add_argument('--checkpoint-freq', type=int, default=5)
    p.add_argument('--arch', default='lstm',
                   choices=['lstm', 'transformer'])
    # Model size (reference torch_language_model.py:41-50).
    p.add_argument('--emsize', type=int, default=650)
    p.add_argument('--nhid', type=int, default=650)
    p.add_argument('--nlayers', type=int, default=2)
    p.add_argument('--nheads', type=int, default=10,
                   help='attention heads (transformer)')
    p.add_argument('--dropout', type=float, default=0.5)
    p.add_argument('--tied', action='store_true')
    p.add_argument('--bptt', type=int, default=35,
                   help='sequence window (reference :52)')
    p.add_argument('--batch-size', type=int, default=20)
    p.add_argument('--epochs', type=int, default=40)
    p.add_argument('--base-lr', type=float, default=1.0)
    p.add_argument('--lr-decay', type=int, nargs='+', default=[20, 30])
    p.add_argument('--warmup-epochs', type=float, default=1)
    p.add_argument('--momentum', type=float, default=0.9)
    p.add_argument('--wd', type=float, default=0.0)
    p.add_argument('--grad-clip', type=float, default=0.25,
                   help='global-norm clip (reference :205)')
    p.add_argument('--seed', type=int, default=42)
    p.add_argument('--no-resume', action='store_true')
    p.add_argument('--seq-parallel', type=int, default=1,
                   help='sequence-parallel degree (transformer only)')
    p.add_argument('--num-slices', type=int,
                   default=int(os.environ.get('KFAC_NUM_SLICES', 1)),
                   help='multi-slice mesh: outer kfac_slice axis over '
                        'N contiguous device slabs (r20). 1 (default) '
                        '= the flat mesh, bit-identical to pre-r20 '
                        'runs. Defaults from KFAC_NUM_SLICES (set by '
                        'the supervisor on slice-failure failover)')
    p.add_argument('--attn-block-size', type=int, default=None,
                   help='single-device memory-efficient attention: fold '
                        'K/V in blocks of this many tokens (O(seq*block) '
                        'live logits instead of O(seq^2)); transformer '
                        'only, ignored under --seq-parallel')
    # K-FAC (reference torch_language_model.py:74-104).
    p.add_argument('--kfac-update-freq', type=int, default=10,
                   help='inverse update interval; 0 disables K-FAC')
    p.add_argument('--inv-pipeline-chunks', type=int, default=1,
                   help='pipeline the per-firing inverse work into K '
                        'cost-balanced chunks fired across the cadence '
                        'window (step-time uniformity, r9); 1 = '
                        'reference parity (monolithic firing). K must '
                        'divide --kfac-update-freq and not exceed the '
                        "model's inverse bucket count")
    p.add_argument('--deferred-factor-reduction', action='store_true',
                   help='accumulate factor statistics locally and '
                        'reduce across replicas once per cadence '
                        'window instead of every factor step (r14 '
                        'compute/communication overlap; exact by EMA '
                        'linearity — off (default) keeps the '
                        'bit-identical eager per-step reduction)')
    p.add_argument('--hierarchical-reduce', action='store_true',
                   help='two-level factor reduction (r20; requires '
                        '--num-slices > 1, mutually exclusive with '
                        '--deferred-factor-reduction): intra-slice '
                        'pmean on ICI every factor step, one bucketed '
                        'inter-slice DCN reduce per cadence window')
    p.add_argument('--fused-factor-contraction', action='store_true',
                   help='r21 fused Pallas factor kernel: symmetric '
                        'packed x.T@x contraction fused with the EMA '
                        'blend (and the r14 accumulator fold) in VMEM '
                        '— only the triangle round-trips HBM. '
                        'Probe-gated: an unsupported backend records a '
                        'pallas_fallback event and runs the stock XLA '
                        'path; off (default) is bit-identical')
    p.add_argument('--fused-precondition', action='store_true',
                   help='r21 fused Pallas precondition kernel: '
                        'bucketed basis-rotation matmuls with the '
                        'KL-clip v·g partial reduced in the kernel '
                        'epilogue (no separate full-tensor clip '
                        'pass). Probe-gated with XLA fallback; off '
                        '(default) is bit-identical')
    p.add_argument('--inv-staleness', type=int, default=0,
                   choices=[0, 1],
                   help='1 = one-window-stale off-critical-path '
                        'inverses (r14): decompositions fire across '
                        "the window's plain steps from the frozen "
                        'window-head factor snapshot, overlapping '
                        'plain compute instead of blocking the mesh '
                        '(needs update-freq/chunks >= 2). '
                        'Convergence-gated like --inv-pipeline-chunks '
                        '(PERF.md r14)')
    p.add_argument('--inv-lowrank-rank', type=int, default=0,
                   help='rank of the randomized truncated '
                        'eigendecomposition for large factor dims '
                        '(r19, arXiv:2206.15397): dims >= '
                        '--inv-lowrank-dim-threshold fire a rank-r '
                        'sketch + warm subspace polish (r*d^2 work) '
                        'instead of the O(d^3) exact decomposition; '
                        'preconditioning adds the damping-only tail '
                        'complement so it stays full-rank correct. '
                        '0 (default) = off, the bit-identical exact '
                        'path; rank >= an engaged dim is a hard error')
    p.add_argument('--inv-lowrank-dim-threshold', type=int,
                   default=2048,
                   help='smallest dense factor dim the low-rank path '
                        'engages (transformer-scale factors by '
                        'default; ignored at --inv-lowrank-rank 0)')
    p.add_argument('--kfac-cov-update-freq', type=int, default=1)
    p.add_argument('--kfac-approx', default='expand',
                   choices=['expand', 'reduce'],
                   help='weight-sharing Kronecker approximation '
                        '(r13, arXiv:2311.00636): expand (default) '
                        'flattens the sequence axis into covariance '
                        'rows — the bit-identical historical path; '
                        'reduce averages activations / sums grads '
                        'over it first — a factor-seq cheaper factor '
                        'update on every attention/MLP Dense, with '
                        'tied in/out embeddings sharing one factor '
                        'pair (see README "Transformer & ViT '
                        'preconditioning")')
    p.add_argument('--inverse-method', default='auto',
                   choices=['auto', 'eigen', 'cholesky', 'newton'],
                   help='auto = per-dim dispatch: eigen below the '
                        'measured cutoff, cholesky above (the TPU '
                        'default that is fast at LM factor dims)')
    p.add_argument('--eigh-method', default='auto',
                   choices=['auto', 'xla', 'jacobi', 'warm'],
                   help='eigen-path decomposition backend; auto = '
                        'warm-start matmul-only basis polish (TPU '
                        'fast path)')
    p.add_argument('--factor-batch-fraction', type=float, default=1.0,
                   help='fraction of the batch used for factor '
                        'statistics (1.0 = reference parity; <1 thins '
                        'the covariance sample within the step)')
    p.add_argument('--eigh-polish-iters', type=int, default=8,
                   help='warm-polish iterations per eigh firing (8: ~1e-3 '
                        'tracking, the measured-equivalent fast default; 16: '
                        '~1e-5)')
    p.add_argument('--stat-decay', type=float, default=0.95)
    p.add_argument('--damping', type=float, default=0.003)
    p.add_argument('--kl-clip', type=float, default=0.001)
    p.add_argument('--skip-layers', nargs='+', default=None,
                   help="default: ['embed', 'decoder'] for lstm (the "
                        'reference preconditions LSTM cells only), [] '
                        'for transformer')
    p.add_argument('--comm-method', default='comm-opt',
                   choices=sorted(optimizers.COMM_METHODS))
    p.add_argument('--grad-worker-fraction', type=float, default=0.25)
    p.add_argument('--symmetry-aware-comm', action='store_true',
                   help='triu-packed factor allreduce (halved bytes)')
    p.add_argument('--bf16-inverses', action='store_true',
                   help='bf16 inverse storage (decompositions stay fp32) '
                        '— at Transformer-XL scale the fp32 inverse '
                        'stacks alone are ~3.2 GB (PERF.md round 5)')
    p.add_argument('--bf16-factors', action='store_true',
                   help='bf16 factor storage/averaging + bf16 covariance '
                        'matmul inputs (matmuls accumulate fp32); the '
                        'reference fp16 factor mode')
    p.add_argument('--bf16-precond', action='store_true',
                   help='bf16 precondition-contraction operands (fp32 '
                        'accumulation; KFAC precond_compute_dtype) — '
                        'the every-step inverse-times-grad matmuls on '
                        'the MXU bf16 path; with --bf16-inverses the '
                        'stored inverses are consumed resident (r6)')
    p.add_argument('--fp16', action='store_true',
                   help='fp16 model compute with dynamic loss scaling + '
                        'overflow-skip (GradScaler parity, reference '
                        'engine.py:38-41,75-80; the reference LM example '
                        'lacks AMP — this completes the CLI surface). On '
                        'TPU, bf16 is the native half mode and needs no '
                        'scaler.')
    obs.cli.add_observability_args(p)
    resil.cli.add_resilience_args(p)
    autotune.cli.add_autotune_args(p)
    return p.parse_args(argv)


def build_model(args, vocab_size, seq_axis=None, dtype=None):
    if dtype is None:
        dtype = jnp.float16 if args.fp16 else None
    if args.arch == 'lstm':
        return lstm_lm.LSTMLanguageModel(
            vocab_size=vocab_size, embedding_dim=args.emsize,
            hidden_dim=args.nhid, num_layers=args.nlayers,
            dropout=args.dropout, tie_weights=args.tied, dtype=dtype)
    return transformer_lm.TransformerLM(
        vocab_size=vocab_size, d_model=args.emsize,
        num_layers=args.nlayers, num_heads=args.nheads,
        max_len=max(args.bptt, 16), dropout=args.dropout,
        tie_weights=args.tied, seq_axis=seq_axis,
        attn_block_size=(args.attn_block_size
                         if seq_axis is None else None),
        dtype=dtype)


def main(argv=None):
    args = parse_args(argv)
    # Preemption handling installs FIRST: a SIGTERM during bring-up
    # should still drain gracefully (r8).
    preemption = resil.cli.install_preemption(args)
    # Multi-host init BEFORE any backend use (single-host no-op; see
    # launch.initialize_multihost / scripts/launch_tpu_pod.sh).
    info = launch.initialize_multihost()
    is_main = info['process_index'] == 0
    n_dev = jax.device_count()
    sp = args.seq_parallel
    if sp > 1 and args.arch != 'transformer':
        raise SystemExit('--seq-parallel requires --arch transformer')
    if args.attn_block_size:
        if args.arch != 'transformer':
            raise SystemExit('--attn-block-size requires '
                             '--arch transformer')
        # Under --seq-parallel the knob is dropped (ring folds per
        # device already); bptt <= block degenerates to exact
        # monolithic attention — both fine. Only a true partial-block
        # split is rejected.
        if (sp == 1 and args.bptt > args.attn_block_size
                and args.bptt % args.attn_block_size):
            raise SystemExit(
                f'--bptt {args.bptt} must be divisible by '
                f'--attn-block-size {args.attn_block_size} '
                '(e.g. --bptt 1024 --attn-block-size 256)')
    if is_main:
        print(f'devices: {n_dev} global / {info["local_devices"]} local '
              f'x {info["process_count"]} processes '
              f'({jax.default_backend()}), seq_parallel={sp}')

    train_ids, val_ids, vocab_size = datasets.get_lm_corpus(args.data_dir)
    if is_main:
        print(f'corpus: {len(train_ids)} train / {len(val_ids)} val '
              f'tokens, vocab {vocab_size}')

    if args.skip_layers is None:
        args.skip_layers = (['embed', 'decoder'] if args.arch == 'lstm'
                            else [])

    seq_axis = seq.SEQ_AXIS if sp > 1 else None
    model = build_model(args, vocab_size, seq_axis=seq_axis)

    cfg = optimizers.OptimConfig(
        base_lr=args.base_lr, momentum=args.momentum,
        weight_decay=args.wd, warmup_epochs=args.warmup_epochs,
        lr_decay=args.lr_decay, workers=1,
        kfac_inv_update_freq=args.kfac_update_freq,
        kfac_cov_update_freq=args.kfac_cov_update_freq,
        inv_pipeline_chunks=args.inv_pipeline_chunks,
        deferred_factor_reduction=args.deferred_factor_reduction,
        hierarchical_reduce=args.hierarchical_reduce,
        fused_factor_contraction=args.fused_factor_contraction,
        fused_precondition=args.fused_precondition,
        inv_staleness=args.inv_staleness,
        kfac_approx=args.kfac_approx,
        damping=args.damping, factor_decay=args.stat_decay,
        kl_clip=args.kl_clip, inverse_method=args.inverse_method,
        inv_lowrank_rank=args.inv_lowrank_rank,
        inv_lowrank_dim_threshold=args.inv_lowrank_dim_threshold,
        eigh_method=args.eigh_method,
        eigh_polish_iters=args.eigh_polish_iters,
        factor_batch_fraction=args.factor_batch_fraction,
        skip_layers=args.skip_layers, comm_method=args.comm_method,
        grad_worker_fraction=args.grad_worker_fraction,
        symmetry_aware_comm=args.symmetry_aware_comm,
        bf16_factors=args.bf16_factors,
        bf16_inverses=args.bf16_inverses,
        bf16_precond=args.bf16_precond,
        kfac_metrics=bool(args.kfac_metrics),
        # --selfheal forces the guard on: the ladder's rung 1 IS the
        # on-device skip-window, and its nonfinite_skips counter is the
        # ladder's primary detection signal (README "Self-healing").
        nonfinite_guard=(obs.cli.wants_guard(args)
                         or resil.cli.wants_selfheal_guard(args)))
    # Tuned-config overlay (fail-closed): the queued apply/fallback
    # events land in the metrics stream once the sink exists below.
    cfg, tune_events = autotune.cli.maybe_apply_tuned(args, cfg)
    cadence_policy = autotune.cli.make_cadence_policy(args)
    tx, lr_schedule, kfac, kfac_sched = optimizers.get_optimizer(model, cfg)
    if kfac is None:
        # --kfac-update-freq 0: plain SGD baseline (reference
        # optimizers.py:28) — same fallback the CNN CLIs expose.
        if sp > 1:
            raise SystemExit('--seq-parallel requires the K-FAC step '
                             '(--kfac-update-freq > 0)')
        if args.kfac_metrics:
            raise SystemExit('--kfac-metrics requires the K-FAC step '
                             '(--kfac-update-freq > 0)')
        if args.fp16:
            raise SystemExit('--fp16 requires the K-FAC step '
                             '(--kfac-update-freq > 0); the SGD baseline '
                             'path does not wire the loss scaler.')
        if cadence_policy is not None:
            raise SystemExit('--cadence-backoff requires the K-FAC '
                             'step (--kfac-update-freq > 0)')
    metrics_sink = obs.cli.make_metrics_sink(
        args, info, meta={'cli': 'train_language_model',
                          'arch': args.arch,
                          'batch_size': args.batch_size,
                          'bptt': args.bptt,
                          'devices': n_dev,
                          'metrics_interval': args.metrics_interval})
    autotune.emit_events(metrics_sink, tune_events)
    shard_meta = {'cli': 'train_language_model'}
    if (args.num_slices > 1
            and info['process_count'] % args.num_slices == 0):
        # Stamp the slice id into the shard meta so the report's
        # straggler section can aggregate per-slice skew rows (r20).
        shard_meta['slice'] = multislice.slice_of_rank(
            info['process_index'], info['process_count'],
            args.num_slices)
    rank_sink = obs.cli.make_rank_shard_sink(args, info, meta=shard_meta)
    # r17 liveness lease (per rank; armed by --heartbeat-dir or the
    # supervisor's KFAC_HEARTBEAT_DIR — None otherwise, and the engine
    # path is byte-identical without it).
    heartbeat = resil.cli.make_heartbeat(args, info)
    if args.grad_clip:
        tx = optax.chain(optax.clip_by_global_norm(args.grad_clip), tx)

    ids0 = jnp.zeros((2, args.bptt), jnp.int32)
    twin = (build_model(args, vocab_size, seq_axis=None)
            if seq_axis else None)
    if kfac is not None:
        variables, _ = kfac.init(jax.random.PRNGKey(args.seed), ids0,
                                 train=False, init_model=twin)
        # Registry provenance (r13): the per-layer resolved approx map
        # rides as a meta record so the recorded run says which layers
        # actually ran reduce/tied (asserted by sharing_smoke.sh).
        obs.cli.emit_layer_meta(metrics_sink, kfac)
    else:
        variables = model.init(jax.random.PRNGKey(args.seed), ids0,
                               train=False)
    params = variables['params']

    # num_slices == 1 returns the flat make_kfac_mesh mesh (the
    # --num-slices 1 bit-identity guarantee); > 1 adds the outer
    # kfac_slice axis over contiguous device slabs.
    mesh = multislice.make_multislice_mesh(
        num_slices=args.num_slices,
        comm_method=optimizers.COMM_METHODS[args.comm_method],
        grad_worker_fraction=args.grad_worker_fraction, seq_parallel=sp)
    # Commit params replicated on the mesh up front: the resume path
    # builds its restore template (like=) from live state, and an
    # uncommitted single-device init would restore a pod checkpoint
    # onto one device (caught by the r8 multihost kill test).
    params = launch.replicate_on_mesh(mesh, params)
    if kfac is not None:
        dkfac = D.DistributedKFAC(kfac, mesh, params)
        kstate = dkfac.init_state(params)
    else:
        dkfac, kstate = None, None
    opt_state = tx.init(params)

    def logits_of(out):
        return out[0] if args.arch == 'lstm' else out

    def loss_fn(out, batch):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits_of(out), batch[1]).mean()

    t_local = args.bptt // sp
    data_axes = (dkfac.data_axes if dkfac is not None
                 else tuple(a for a in D.KFAC_AXES
                            if a in mesh.axis_names))

    def model_kwargs_fn(batch):
        # Per-device dropout key: fold the step key with the device's
        # linear mesh index so masks decorrelate across shards.
        idx = jax.lax.axis_index(data_axes[0])
        for ax in data_axes[1:]:
            idx = idx * jax.lax.psum(1, ax) + jax.lax.axis_index(ax)
        kwargs = {'train': True,
                  'rngs': {'dropout': jax.random.fold_in(batch[2], idx)}}
        if seq_axis:
            kwargs['pos_offset'] = (
                jax.lax.axis_index(seq.SEQ_AXIS) * t_local)
        return kwargs

    batch_axes = multislice.batch_axes(mesh)
    data_spec = (P(batch_axes, seq.SEQ_AXIS) if seq_axis
                 else P(batch_axes))
    if dkfac is not None:
        step_fn = dkfac.build_train_step(
            loss_fn, tx, model_kwargs_fn=model_kwargs_fn,
            batch_spec=(data_spec, data_spec, P()),
            loss_scale='dynamic' if args.fp16 else None)
    else:  # --kfac-update-freq 0: plain SGD (reference optimizers.py:28)
        step_fn = engine.build_sgd_train_step(
            model, loss_fn, tx, mesh,
            model_kwargs_fn=model_kwargs_fn,
            batch_spec=(data_spec, data_spec, P()),
            metrics_fn=lambda out, b: {})

    def eval_loss(out, batch):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits_of(out), batch[1]).mean()

    eval_step = engine.make_eval_step(
        build_model(args, vocab_size, seq_axis=None), eval_loss, None,
        model_args_fn=lambda b: (b[0],), model_kwargs={'train': False},
        metrics_fn=lambda o, b: {})
    # Straggler barrier probe: shards requested (or the cadence-backoff
    # policy armed) + a K-FAC step (the probe reduces over the K-FAC
    # data axes).
    barrier_probe = (dkfac.build_barrier_probe()
                     if (rank_sink is not None
                         or cadence_policy is not None)
                     and dkfac is not None
                     else None)

    state = engine.TrainState(params=params, opt_state=opt_state,
                              kfac_state=kstate,
                              extra_vars=(
                                  {'loss_scale':
                                   fp16_lib.init_loss_scale()}
                                  if args.fp16 else {}))
    if dkfac is None and args.checkpoint_dir == './checkpoints/lm':
        # Keep the SGD comparison's checkpoints apart from a K-FAC run's
        # (the state trees differ, so cross-mode resume cannot work).
        args.checkpoint_dir += '-sgd'
    mgr = ckpt_lib.CheckpointManager(args.checkpoint_dir)
    step_mgr = resil.cli.make_step_manager(args)
    # The saving world, recorded in every bundle's scalars so a
    # relaunch on a grown/shrunk pod can reshard instead of cold
    # restarting (elastic resume — README "Elastic training").
    topo = elastic_lib.TopologySpec.of_mesh(
        mesh, distribute_layer_factors=(
            dkfac.distribute_layer_factors if dkfac else None))

    def bundle_fn(st, step_in_epoch, integrity=True):
        # Must match the SAVED structure exactly (orbax StandardRestore
        # is strict): scheduler states + the resume-point scalars
        # (MIGRATION.md "Checkpoint format").
        return ckpt_lib.bundle_state(
            st.params, st.opt_state,
            dkfac.state_dict(st.kfac_state) if dkfac else {},
            st.extra_vars,
            schedulers={'kfac': kfac_sched} if kfac_sched else None,
            topology=topo,
            integrity=integrity,
            step=st.step, epoch=st.epoch, step_in_epoch=step_in_epoch,
            data_seed=args.seed)

    start_epoch, start_offset = 0, 0
    # integrity='template': the like= tree needs the checksum FIELD
    # (orbax structures are exact) but hashing the whole live state
    # for a digest nobody reads was pure startup cost.
    resumed = resil.cli.resume(args, mgr, step_mgr,
                               bundle_fn(state, 0,
                                         integrity='template'),
                               sink=metrics_sink, verbose=is_main,
                               elastic=elastic_lib.ElasticResume(
                                   mesh=mesh, dkfac=dkfac,
                                   params=state.params))
    if resumed is not None:
        restored, start_epoch, start_offset, _src = resumed
        state.params = restored['params']
        state.opt_state = restored['opt_state']
        if dkfac:
            state.kfac_state = dkfac.load_state_dict(
                restored['kfac'], state.params)
        state.extra_vars = restored['extra_vars']
        state.epoch = start_epoch
        # Restore the host step counter: the engine's static cadence is
        # driven by it, so it must stay in phase with kstate['step'].
        state.step = int(restored['scalars']['step'])
        if kfac_sched:
            kfac_sched.step(start_epoch)
    step_ckpt = resil.cli.make_step_checkpointer(
        args, step_mgr, bundle_fn, preemption=preemption,
        sink=metrics_sink, start_step=state.step)
    # r16 self-healing ladder (None when --selfheal is off — the
    # engine then runs the byte-identical pre-r16 path).
    selfheal_ctl = resil.cli.make_selfheal(
        args, kfac=kfac, params=params, sink=metrics_sink)

    def batches(epoch, skip=0):
        # skip= is the mid-epoch resume offset; the per-step dropout
        # keys fold the ABSOLUTE window index so the replayed tail is
        # bit-identical to the uninterrupted epoch's.
        root = jax.random.PRNGKey(args.seed * 1000 + epoch)
        for i, (x, y) in enumerate(datasets.bptt_batches(
                train_ids, args.batch_size, args.bptt,
                shuffle_offset=True, seed=args.seed, epoch=epoch,
                skip_batches=skip), start=skip):
            yield x, y, jax.random.fold_in(root, i)

    writer = engine.TensorBoardWriter(args.log_dir) if is_main else None
    t_start = time.perf_counter()
    try:
        epoch = start_epoch
        while epoch < args.epochs:
            skip = start_offset if epoch == start_epoch else 0
            # Drain a preemption notice that landed during eval/
            # checkpointing of the previous epoch (forced save + exit).
            step_ckpt.poll(state, skip)
            lr = lr_schedule(epoch)
            state.opt_state = optimizers.set_lr(state.opt_state, lr)
            hyper = {'lr': lr,
                     **(kfac_sched.params() if kfac_sched else {})}
            raw = resil.faults.poison_at(batches(epoch, skip),
                                         step_ckpt.plan,
                                         first_step=state.step)
            try:
                with obs.cli.profile_epoch(args, info, epoch,
                                           start_epoch):
                    train_m = engine.train_epoch(
                        step_fn, state,
                        launch.global_batches(
                            mesh, raw,
                            batch_spec=(data_spec, data_spec, P())),
                        hyper, log_writer=writer, verbose=is_main,
                        metrics_sink=metrics_sink,
                        checkpointer=step_ckpt,
                        start_step_in_epoch=skip,
                        rank_sink=rank_sink,
                        barrier_probe=barrier_probe,
                        straggler_sample_every=(
                            args.straggler_sample_every),
                        memory_interval=args.memory_interval,
                        cadence_policy=cadence_policy,
                        selfheal=selfheal_ctl,
                        heartbeat=heartbeat)
            except resil.selfheal.Rollback as rb:
                # Rung 4: restore the newest VERIFIED pre-fault step
                # checkpoint into the live state and keep training IN
                # THIS PROCESS (die-and-relaunch is the rung after).
                start_epoch, start_offset = resil.selfheal.\
                    handle_rollback(
                        rb, args=args, step_mgr=step_mgr,
                        like=bundle_fn(state, 0,
                                       integrity='template'),
                        state=state,
                        dkfac=dkfac, sink=metrics_sink,
                        controller=selfheal_ctl,
                        kfac_sched=kfac_sched, checkpointer=step_ckpt,
                        verbose=is_main)
                epoch = start_epoch
                continue
            val_m = engine.evaluate(
                eval_step, state,
                launch.global_batches(
                    mesh,
                    datasets.bptt_batches(val_ids, args.batch_size,
                                          args.bptt),
                    batch_spec=(data_spec, data_spec)),
                log_writer=writer, verbose=is_main)
            if is_main and 'loss' in train_m:
                print(f'epoch {epoch}: train ppl '
                      f'{math.exp(min(train_m["loss"], 20)):.2f}, '
                      f'val ppl '
                      f'{math.exp(min(val_m["loss"], 20)):.2f}')
            if kfac_sched:
                kfac_sched.step(epoch + 1)
            if (epoch + 1) % args.checkpoint_freq == 0 or \
                    epoch == args.epochs - 1:
                # force=: a cross-epoch self-heal rollback replays
                # epochs whose bundles already exist on disk; the
                # replayed save must overwrite, not crash (the step
                # checkpointer already saves with force for the same
                # reason).
                mgr.save(epoch, bundle_fn(state, 0), force=True)
            epoch += 1
    except resil.preemption.Preempted as p:
        # The step checkpoint is already durable (blocking save).
        step_ckpt.close()
        mgr.wait_until_finished()
        if metrics_sink is not None:
            metrics_sink.close()
        if rank_sink is not None:
            rank_sink.close()
        if heartbeat is not None:
            heartbeat.close()
        if is_main:
            print(f'preempted ({p.reason}) at global step '
                  f'{p.global_step}; checkpoint saved — exiting '
                  f'{resil.preemption.RELAUNCH_EXIT_CODE} for relaunch')
        return resil.preemption.RELAUNCH_EXIT_CODE
    step_ckpt.close()
    mgr.wait_until_finished()  # async saves: durable before exit
    if metrics_sink is not None:
        metrics_sink.close()
    if rank_sink is not None:
        rank_sink.close()
    if heartbeat is not None:
        heartbeat.close()
    if writer is not None:
        writer.flush()
    if is_main:
        print(f'total: {time.perf_counter() - t_start:.1f}s')
    return 0


if __name__ == '__main__':
    sys.exit(main())
