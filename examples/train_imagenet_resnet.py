"""ImageNet ResNet training with distributed K-FAC on a TPU mesh.

TPU-native counterpart of the reference entry point
(examples/torch_imagenet_resnet.py): same flag surface and recipe — 55
epochs, lr decay @ 25/35/40/45/50, base-lr 0.0125 per worker linearly
scaled, 5 warmup epochs, label smoothing 0.1, wd 5e-5
(torch_imagenet_resnet.py:57-70), K-FAC inv every 100 iters / factors
every 10 (:75-78) — on the jitted SPMD train step instead of DDP + hooks.

Run:
    python examples/train_imagenet_resnet.py --epochs 55 --model resnet50
Without --data-dir a synthetic ImageNet-shaped set keeps it runnable
offline (the bench/smoke path).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from distributed_kfac_pytorch_tpu import autotune
from distributed_kfac_pytorch_tpu import capture as capture_lib
from distributed_kfac_pytorch_tpu import elastic as elastic_lib
from distributed_kfac_pytorch_tpu import fp16 as fp16_lib
from distributed_kfac_pytorch_tpu import launch
from distributed_kfac_pytorch_tpu import observability as obs
from distributed_kfac_pytorch_tpu import resilience as resil
from distributed_kfac_pytorch_tpu import multislice
from distributed_kfac_pytorch_tpu.models import imagenet_resnet, vit
from distributed_kfac_pytorch_tpu.parallel import distributed as D
from distributed_kfac_pytorch_tpu.training import (
    checkpoint as ckpt_lib,
    datasets,
    engine,
    optimizers,
    utils,
)

from distributed_kfac_pytorch_tpu.utils import enable_compilation_cache

enable_compilation_cache()  # persistent compile cache (KFAC_COMPILE_CACHE=0 disables)


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description='ImageNet ResNet + distributed K-FAC (TPU-native)')
    # Training settings (reference torch_imagenet_resnet.py:40-70).
    p.add_argument('--data-dir', default=None,
                   help='ImageFolder-style tree (synthetic if absent)')
    p.add_argument('--log-dir', default='./logs/imagenet')
    p.add_argument('--checkpoint-dir', default='./checkpoints/imagenet')
    p.add_argument('--checkpoint-freq', type=int, default=5)
    p.add_argument('--model', default='resnet50',
                   help="resnet<depth> or 'vit_<tiny|small|base>' "
                        '(ViT-*/16; --image-size must divide by 16)')
    p.add_argument('--image-size', type=int, default=224)
    p.add_argument('--batch-size', type=int, default=256,
                   help='global batch size')
    p.add_argument('--val-batch-size', type=int, default=256)
    p.add_argument('--epochs', type=int, default=55)
    p.add_argument('--base-lr', type=float, default=0.0125,
                   help='per-worker lr, linearly scaled by worker count')
    p.add_argument('--lr-decay', type=int, nargs='+',
                   default=[25, 35, 40, 45, 50])
    p.add_argument('--warmup-epochs', type=float, default=5)
    p.add_argument('--momentum', type=float, default=0.9)
    p.add_argument('--wd', type=float, default=5e-5)
    p.add_argument('--label-smoothing', type=float, default=0.1)
    p.add_argument('--grad-accum', type=int, default=1,
                   help='micro-batches per step (batches-per-allreduce)')
    p.add_argument('--precise-bn-batches', type=int, default=0,
                   help='re-estimate BN running statistics over this '
                        'many forward-only train batches before each '
                        'eval (precise-BN — the round-5 mitigation for '
                        'BN stats lagging large preconditioned steps; '
                        '0 = off). Eval-only: training EWMA state is '
                        'untouched.')
    p.add_argument('--bn-momentum', type=float, default=None,
                   help='BatchNorm running-stat EWMA momentum (flax '
                        'convention; default 0.9 = torch momentum 0.1; '
                        'rejected for models without BatchNorm)')
    p.add_argument('--remat', action='store_true',
                   help='block-level gradient checkpointing: ~1/3 extra '
                        'forward FLOPs for O(depth) activation memory — '
                        'fits larger monolithic batches (the bf16 K-FAC '
                        'capture path OOMs at b128@224 without it)')
    p.add_argument('--seed', type=int, default=42)
    p.add_argument('--no-resume', action='store_true')
    # K-FAC hyperparameters (reference torch_imagenet_resnet.py:71-105).
    p.add_argument('--kfac-update-freq', type=int, default=100,
                   help='inverse update interval; 0 disables K-FAC')
    p.add_argument('--inv-pipeline-chunks', type=int, default=1,
                   help='pipeline the per-firing inverse work into K '
                        'cost-balanced chunks fired across the cadence '
                        'window (step-time uniformity, r9); 1 = '
                        'reference parity (monolithic firing). K must '
                        'divide --kfac-update-freq and not exceed the '
                        "model's inverse bucket count")
    p.add_argument('--deferred-factor-reduction', action='store_true',
                   help='accumulate factor statistics locally and '
                        'reduce across replicas once per cadence '
                        'window instead of every factor step (r14 '
                        'compute/communication overlap; exact by EMA '
                        'linearity — off (default) keeps the '
                        'bit-identical eager per-step reduction)')
    p.add_argument('--hierarchical-reduce', action='store_true',
                   help='two-level factor reduction (r20; requires '
                        '--num-slices > 1, mutually exclusive with '
                        '--deferred-factor-reduction): intra-slice '
                        'pmean on ICI every factor step, one bucketed '
                        'inter-slice DCN reduce per cadence window')
    p.add_argument('--num-slices', type=int,
                   default=int(os.environ.get('KFAC_NUM_SLICES', 1)),
                   help='multi-slice mesh: outer kfac_slice axis over '
                        'N contiguous device slabs (r20). 1 (default) '
                        '= the flat mesh, bit-identical to pre-r20 '
                        'runs. Defaults from KFAC_NUM_SLICES (set by '
                        'the supervisor on slice-failure failover)')
    p.add_argument('--fused-factor-contraction', action='store_true',
                   help='r21 fused Pallas factor kernel: symmetric '
                        'packed x.T@x contraction fused with the EMA '
                        'blend (and the r14 accumulator fold) in VMEM '
                        '— only the triangle round-trips HBM. '
                        'Probe-gated: an unsupported backend records a '
                        'pallas_fallback event and runs the stock XLA '
                        'path; off (default) is bit-identical')
    p.add_argument('--fused-precondition', action='store_true',
                   help='r21 fused Pallas precondition kernel: '
                        'bucketed basis-rotation matmuls with the '
                        'KL-clip v·g partial reduced in the kernel '
                        'epilogue (no separate full-tensor clip '
                        'pass). Probe-gated with XLA fallback; off '
                        '(default) is bit-identical')
    p.add_argument('--inv-staleness', type=int, default=0,
                   choices=[0, 1],
                   help='1 = one-window-stale off-critical-path '
                        'inverses (r14): decompositions fire across '
                        "the window's plain steps from the frozen "
                        'window-head factor snapshot, overlapping '
                        'plain compute instead of blocking the mesh '
                        '(needs update-freq/chunks >= 2). '
                        'Convergence-gated like --inv-pipeline-chunks '
                        '(PERF.md r14)')
    p.add_argument('--inv-lowrank-rank', type=int, default=0,
                   help='rank of the randomized truncated '
                        'eigendecomposition for large factor dims '
                        '(r19, arXiv:2206.15397): dims >= '
                        '--inv-lowrank-dim-threshold fire a rank-r '
                        'sketch + warm subspace polish (r*d^2 work) '
                        'instead of the O(d^3) exact decomposition; '
                        'preconditioning adds the damping-only tail '
                        'complement so it stays full-rank correct. '
                        '0 (default) = off, the bit-identical exact '
                        'path; rank >= an engaged dim is a hard error')
    p.add_argument('--inv-lowrank-dim-threshold', type=int,
                   default=2048,
                   help='smallest dense factor dim the low-rank path '
                        'engages (transformer-scale factors by '
                        'default; ignored at --inv-lowrank-rank 0)')
    p.add_argument('--kfac-cov-update-freq', type=int, default=10)
    p.add_argument('--kfac-approx', default='expand',
                   choices=['expand', 'reduce'],
                   help='weight-sharing Kronecker approximation (r13, '
                        'arXiv:2311.00636): expand (default) is the '
                        'bit-identical historical path; reduce '
                        'collapses the shared patch axis before the '
                        'covariance — the paper\'s ViT treatment '
                        '(patch-embed conv + every encoder Dense); a '
                        'no-op for plain conv nets')
    p.add_argument('--kfac-update-freq-alpha', type=float, default=10)
    p.add_argument('--kfac-update-freq-decay', type=int, nargs='+',
                   default=[])
    p.add_argument('--inverse-method', default='auto',
                   choices=['auto', 'eigen', 'cholesky', 'newton'],
                   help='auto = per-dim dispatch: eigen below the '
                        'measured cutoff, cholesky above (the TPU '
                        'default that is fast at flagship factor dims)')
    p.add_argument('--eigh-method', default='auto',
                   choices=['auto', 'xla', 'jacobi', 'warm'],
                   help='eigen-path decomposition backend; auto = '
                        'warm-start matmul-only basis polish (TPU '
                        'fast path)')
    p.add_argument('--factor-batch-fraction', type=float, default=1.0,
                   help='fraction of the batch used for factor '
                        'statistics (1.0 = reference parity; <1 thins '
                        'the covariance sample within the step)')
    p.add_argument('--eigh-polish-iters', type=int, default=8,
                   help='warm-polish iterations per eigh firing (8: ~1e-3 '
                        'tracking, the measured-equivalent fast default; 16: '
                        '~1e-5)')
    p.add_argument('--stat-decay', type=float, default=0.95)
    p.add_argument('--damping', type=float, default=0.001)
    p.add_argument('--damping-alpha', type=float, default=0.5)
    p.add_argument('--damping-decay', type=int, nargs='+', default=[])
    p.add_argument('--kl-clip', type=float, default=0.001)
    p.add_argument('--skip-layers', nargs='+', default=[])
    p.add_argument('--comm-method', default='comm-opt',
                   choices=sorted(optimizers.COMM_METHODS))
    p.add_argument('--grad-worker-fraction', type=float, default=0.25)
    p.add_argument('--coallocate-layer-factors', action='store_true',
                   help='place A and G of a layer on the same worker '
                        '(reference --coallocate-layer-factors)')
    p.add_argument('--symmetry-aware-comm', action='store_true',
                   help='triu-packed factor allreduce (halved bytes)')
    p.add_argument('--bf16-factors', action='store_true',
                   help='bf16 factor storage/averaging + bf16 covariance '
                        'matmul inputs (matmuls accumulate fp32); the '
                        'reference fp16 factor mode')
    p.add_argument('--bf16-inverses', action='store_true',
                   help='bf16 inverse storage (decompositions stay '
                        'fp32); with --bf16-factors this is the '
                        'measured b256 production config on 16 GB '
                        'chips (PERF.md round 5)')
    p.add_argument('--bf16-precond', action='store_true',
                   help='bf16 precondition-contraction operands (fp32 '
                        'accumulation; KFAC precond_compute_dtype) — '
                        'the every-step inverse-times-grad matmuls on '
                        'the MXU bf16 path; with --bf16-inverses the '
                        'stored inverses are consumed resident (r6)')
    p.add_argument('--fp16', action='store_true',
                   help='fp16 model compute with dynamic loss scaling + '
                        'overflow-skip (GradScaler parity — the '
                        "reference's production ImageNet recipe passes "
                        '--fp16, launch_node_torch_imagenet.sh:73-87; '
                        'engine.py:38-41,75-80). On TPU, bf16 is the '
                        'native half mode and needs no scaler; --fp16 '
                        'exists for exact reference-recipe parity.')
    obs.cli.add_observability_args(p)
    resil.cli.add_resilience_args(p)
    autotune.cli.add_autotune_args(p)
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    # Preemption handling installs FIRST: a SIGTERM during bring-up
    # should still drain gracefully (r8).
    preemption = resil.cli.install_preemption(args)
    # Multi-host init BEFORE any backend use (reference analogue:
    # init_process_group at torch_imagenet_resnet.py:113, driven by
    # scripts/launch_tpu_pod.sh; single-host no-op).
    info = launch.initialize_multihost()
    is_main = info['process_index'] == 0
    n_dev = jax.device_count()
    if is_main:
        print(f'devices: {n_dev} global / {info["local_devices"]} local '
              f'x {info["process_count"]} processes '
              f'({jax.default_backend()})')

    data = datasets.get_imagenet(args.data_dir,
                                 image_size=args.image_size)
    nproc = info['process_count']
    batches_local = False  # True: iterators yield per-process shards
    if isinstance(data[0], tuple):
        (train_x, train_y), (val_x, val_y) = data
        # skip= is the mid-epoch resume offset (resilience r8): the
        # seeded numpy pipeline replays the remaining batches
        # bit-identically (see resilience.dataiter).
        train_iter_fn = lambda epoch, skip=0: datasets.epoch_batches(
            train_x, train_y, args.batch_size, seed=args.seed,
            epoch=epoch, skip_batches=skip)
        val_iter_fn = lambda: datasets.epoch_batches(
            val_x, val_y, args.val_batch_size, shuffle=False)
    else:
        train_ds, val_ds = data
        tb, vb = args.batch_size, args.val_batch_size
        if nproc > 1:
            # Shard the input pipeline per process (the reference's
            # DistributedSampler analogue, datasets.py:57-63) so no host
            # pays the full global decode cost; global_batches then
            # assembles the local shards without re-slicing.
            if tb % nproc or vb % nproc:
                raise SystemExit(
                    f'batch sizes ({tb}, {vb}) must divide evenly over '
                    f'{nproc} processes')
            train_ds = train_ds.shard(nproc, info['process_index'])
            val_ds = val_ds.shard(nproc, info['process_index'])
            tb, vb = tb // nproc, vb // nproc
            batches_local = True
        # tf.data path: mid-epoch resume is BEST-EFFORT — the model
        # state restores exactly, but shuffle order is per iterator
        # creation (not epoch-seeded), so the skipped-batch replay is
        # not bit-identical here (resilience.dataiter documents this;
        # the numpy pipelines above carry the replay guarantee).
        train_iter_fn = lambda epoch, skip=0: (
            (x.numpy(), y.numpy()) for x, y in
            train_ds.batch(tb, drop_remainder=True).skip(skip))
        val_iter_fn = lambda: (
            (x.numpy(), y.numpy()) for x, y in
            val_ds.batch(vb, drop_remainder=True))

    dtype = jnp.float16 if args.fp16 else jnp.float32
    # Strict name parsing: exactly 'vit' or 'vit_<size>'. A prefix match
    # alone would let 'vitbase'/'vit-base' fall through and silently
    # train the default config (ADVICE r5).
    model_head, _, vit_size = args.model.partition('_')
    if model_head == 'vit':
        if args.remat:
            raise SystemExit('--remat is the ResNet block-level knob; '
                             'for ViT memory use chunked attention '
                             '(models/vit.py attn_block_size)')
        model = vit.get_model(1000, vit_size or 'small', dtype=dtype)
    elif args.model.startswith('vit'):
        raise SystemExit(
            f'unknown model {args.model!r}: ViT configs are spelled '
            "'vit' or 'vit_<tiny|small|base>'")
    else:
        model = imagenet_resnet.get_model(
            args.model, dtype=dtype,
            bn_momentum=0.9 if args.bn_momentum is None
            else args.bn_momentum, remat=args.remat)
    cfg = optimizers.OptimConfig(
        base_lr=args.base_lr, momentum=args.momentum,
        weight_decay=args.wd, warmup_epochs=args.warmup_epochs,
        lr_decay=args.lr_decay, workers=n_dev,
        kfac_inv_update_freq=args.kfac_update_freq,
        kfac_cov_update_freq=args.kfac_cov_update_freq,
        inv_pipeline_chunks=args.inv_pipeline_chunks,
        deferred_factor_reduction=args.deferred_factor_reduction,
        hierarchical_reduce=args.hierarchical_reduce,
        fused_factor_contraction=args.fused_factor_contraction,
        fused_precondition=args.fused_precondition,
        inv_staleness=args.inv_staleness,
        kfac_approx=args.kfac_approx,
        inv_lowrank_rank=args.inv_lowrank_rank,
        inv_lowrank_dim_threshold=args.inv_lowrank_dim_threshold,
        damping=args.damping, factor_decay=args.stat_decay,
        kl_clip=args.kl_clip, inverse_method=args.inverse_method,
        eigh_method=args.eigh_method,
        eigh_polish_iters=args.eigh_polish_iters,
        factor_batch_fraction=args.factor_batch_fraction,
        skip_layers=args.skip_layers, comm_method=args.comm_method,
        grad_worker_fraction=args.grad_worker_fraction,
        symmetry_aware_comm=args.symmetry_aware_comm,
        damping_alpha=args.damping_alpha,
        damping_schedule=args.damping_decay,
        kfac_update_freq_alpha=args.kfac_update_freq_alpha,
        kfac_update_freq_schedule=args.kfac_update_freq_decay,
        bf16_factors=args.bf16_factors,
        bf16_inverses=args.bf16_inverses,
        bf16_precond=args.bf16_precond,
        kfac_metrics=bool(args.kfac_metrics),
        # --selfheal forces the guard on: the ladder's rung 1 IS the
        # on-device skip-window (README "Self-healing").
        nonfinite_guard=(obs.cli.wants_guard(args)
                         or resil.cli.wants_selfheal_guard(args)))
    # Tuned-config overlay (fail-closed): the queued apply/fallback
    # events land in the metrics stream once the sink exists below.
    cfg, tune_events = autotune.cli.maybe_apply_tuned(args, cfg)
    cadence_policy = autotune.cli.make_cadence_policy(args)
    tx, lr_schedule, kfac, kfac_sched = optimizers.get_optimizer(model, cfg)
    if args.kfac_metrics and kfac is None:
        raise SystemExit('--kfac-metrics requires the K-FAC step '
                         '(--kfac-update-freq > 0)')
    if cadence_policy is not None and kfac is None:
        raise SystemExit('--cadence-backoff requires the K-FAC step '
                         '(--kfac-update-freq > 0)')
    metrics_sink = obs.cli.make_metrics_sink(
        args, info, meta={'cli': 'train_imagenet_resnet',
                          'model': args.model,
                          'batch_size': args.batch_size,
                          'devices': n_dev,
                          'metrics_interval': args.metrics_interval})
    autotune.emit_events(metrics_sink, tune_events)
    shard_meta = {'cli': 'train_imagenet_resnet'}
    if (args.num_slices > 1
            and info['process_count'] % args.num_slices == 0):
        # Slice id into the shard meta -> per-slice skew rows in the
        # report's straggler section (r20).
        shard_meta['slice'] = multislice.slice_of_rank(
            info['process_index'], info['process_count'],
            args.num_slices)
    rank_sink = obs.cli.make_rank_shard_sink(args, info, meta=shard_meta)
    # r17 liveness lease (per rank; armed by --heartbeat-dir or the
    # supervisor's KFAC_HEARTBEAT_DIR — None otherwise, and the engine
    # path is byte-identical without it).
    heartbeat = resil.cli.make_heartbeat(args, info)

    x0 = jnp.zeros((2, args.image_size, args.image_size, 3), jnp.float32)
    if kfac is not None:
        variables, _ = kfac.init(jax.random.PRNGKey(args.seed), x0)
        obs.cli.emit_layer_meta(metrics_sink, kfac)
    else:
        variables = model.init(jax.random.PRNGKey(args.seed), x0)
    params = variables['params']
    # batch_stats exists only for BatchNorm models (absent for ViT —
    # stateless LayerNorm).
    extra = capture_lib.extra_vars_of(variables)
    mutable = ('batch_stats',) if 'batch_stats' in extra else ()
    if args.precise_bn_batches > 0 and not mutable:
        raise SystemExit('--precise-bn-batches requires a BatchNorm '
                         f'model; {args.model!r} has no batch_stats')
    if args.bn_momentum is not None and not mutable:
        raise SystemExit('--bn-momentum requires a BatchNorm model; '
                         f'{args.model!r} has no batch_stats')
    if args.fp16:
        if kfac is None:
            raise SystemExit('--fp16 requires the K-FAC step '
                             '(--kfac-update-freq > 0); the SGD baseline '
                             'path does not wire the loss scaler.')
        extra['loss_scale'] = fp16_lib.init_loss_scale()

    # num_slices == 1 returns the flat make_kfac_mesh mesh (the
    # --num-slices 1 bit-identity guarantee).
    mesh = multislice.make_multislice_mesh(
        num_slices=args.num_slices,
        comm_method=optimizers.COMM_METHODS[args.comm_method],
        grad_worker_fraction=args.grad_worker_fraction)
    # Commit params/extra replicated on the mesh up front: the resume
    # path builds its restore template (like=) from live state, and an
    # uncommitted single-device init would restore a pod checkpoint
    # onto one device (caught by the r8 multihost kill test).
    params, extra = launch.replicate_on_mesh(mesh, (params, extra))
    opt_state = tx.init(params)

    def loss_fn(out, batch):
        return utils.label_smooth_loss(out, batch[1],
                                       args.label_smoothing)

    def metrics_fn(out, batch):
        return {'acc': utils.accuracy(out, batch[1])}

    if kfac is not None:
        dkfac = D.DistributedKFAC(
            kfac, mesh, params,
            distribute_layer_factors=(
                False if args.coallocate_layer_factors else None))
        kstate = dkfac.init_state(params)
        step_fn = dkfac.build_train_step(
            loss_fn, tx, metrics_fn=metrics_fn,
            mutable_cols=mutable,
            grad_accum_steps=args.grad_accum,
            loss_scale='dynamic' if args.fp16 else None)
    else:  # --kfac-update-freq 0: plain SGD (reference optimizers.py:28)
        dkfac, kstate = None, None
        step_fn = engine.build_sgd_train_step(
            model, loss_fn, tx, mesh, metrics_fn=metrics_fn,
            mutable_cols=mutable,
            grad_accum_steps=args.grad_accum)
    eval_step = engine.make_eval_step(
        model, lambda out, b: utils.label_smooth_loss(out, b[1], 0.0),
        mesh, model_args_fn=lambda b: (b[0],),
        model_kwargs={'train': False})
    # Straggler barrier probe: shards requested (or the cadence-backoff
    # policy armed) + a K-FAC step (the probe reduces over the K-FAC
    # data axes).
    barrier_probe = (dkfac.build_barrier_probe()
                     if (rank_sink is not None
                         or cadence_policy is not None)
                     and dkfac is not None
                     else None)

    state = engine.TrainState(params=params, opt_state=opt_state,
                              kfac_state=kstate, extra_vars=extra)
    if dkfac is None and args.checkpoint_dir == './checkpoints/imagenet':
        # Keep the SGD comparison's checkpoints apart from a K-FAC run's
        # (the state trees differ, so cross-mode resume cannot work).
        args.checkpoint_dir += '-sgd'
    mgr = ckpt_lib.CheckpointManager(args.checkpoint_dir)
    step_mgr = resil.cli.make_step_manager(args)
    # The saving world, recorded in every bundle's scalars so a
    # relaunch on a grown/shrunk pod can reshard instead of cold
    # restarting (elastic resume — README "Elastic training").
    topo = elastic_lib.TopologySpec.of_mesh(
        mesh, distribute_layer_factors=(
            dkfac.distribute_layer_factors if dkfac else None))

    def bundle_fn(st, step_in_epoch, integrity=True):
        # Must match the SAVED structure exactly (orbax StandardRestore
        # is strict): scheduler states + the resume-point scalars
        # (MIGRATION.md "Checkpoint format").
        return ckpt_lib.bundle_state(
            st.params, st.opt_state,
            dkfac.state_dict(st.kfac_state) if dkfac else {},
            st.extra_vars,
            schedulers={'kfac': kfac_sched} if kfac_sched else None,
            topology=topo,
            integrity=integrity,
            step=st.step, epoch=st.epoch, step_in_epoch=step_in_epoch,
            data_seed=args.seed)

    start_epoch, start_offset = 0, 0
    # integrity='template': the like= tree needs the checksum FIELD
    # (orbax structures are exact) but hashing the whole live state
    # for a digest nobody reads was pure startup cost.
    resumed = resil.cli.resume(args, mgr, step_mgr,
                               bundle_fn(state, 0,
                                         integrity='template'),
                               sink=metrics_sink, verbose=is_main,
                               elastic=elastic_lib.ElasticResume(
                                   mesh=mesh, dkfac=dkfac,
                                   params=state.params))
    if resumed is not None:
        restored, start_epoch, start_offset, _src = resumed
        state.params = restored['params']
        state.opt_state = restored['opt_state']
        if dkfac:
            state.kfac_state = dkfac.load_state_dict(
                restored['kfac'], state.params)
        state.extra_vars = restored['extra_vars']
        state.epoch = start_epoch
        state.step = int(restored['scalars']['step'])
        if kfac_sched:
            kfac_sched.step(start_epoch)
    step_ckpt = resil.cli.make_step_checkpointer(
        args, step_mgr, bundle_fn, preemption=preemption,
        sink=metrics_sink, start_step=state.step)
    # r16 self-healing ladder (None when --selfheal is off — the
    # engine then runs the byte-identical pre-r16 path).
    selfheal_ctl = resil.cli.make_selfheal(
        args, kfac=kfac, params=state.params, sink=metrics_sink)

    writer = engine.TensorBoardWriter(args.log_dir) if is_main else None
    bn_steps = (engine.make_precise_bn_steps(model, mesh)
                if args.precise_bn_batches > 0 else None)
    t_start = time.perf_counter()
    try:
        epoch = start_epoch
        while epoch < args.epochs:
            skip = start_offset if epoch == start_epoch else 0
            # Drain a preemption notice that landed during eval/
            # checkpointing of the previous epoch (forced save + exit).
            step_ckpt.poll(state, skip)
            lr = lr_schedule(epoch)
            state.opt_state = optimizers.set_lr(state.opt_state, lr)
            hyper = {'lr': lr,
                     **(kfac_sched.params() if kfac_sched else {})}
            raw = resil.faults.poison_at(train_iter_fn(epoch, skip),
                                         step_ckpt.plan,
                                         first_step=state.step)
            try:
                with obs.cli.profile_epoch(args, info, epoch,
                                           start_epoch):
                    train_m = engine.train_epoch(
                        step_fn, state,
                        launch.global_batches(
                            mesh, raw, already_sharded=batches_local),
                        hyper, log_writer=writer, verbose=is_main,
                        metrics_sink=metrics_sink,
                        checkpointer=step_ckpt,
                        start_step_in_epoch=skip,
                        rank_sink=rank_sink,
                        barrier_probe=barrier_probe,
                        straggler_sample_every=(
                            args.straggler_sample_every),
                        memory_interval=args.memory_interval,
                        cadence_policy=cadence_policy,
                        selfheal=selfheal_ctl,
                        heartbeat=heartbeat)
            except resil.selfheal.Rollback as rb:
                # Rung 4: restore the newest VERIFIED pre-fault step
                # checkpoint into the live state and keep training IN
                # THIS PROCESS (die-and-relaunch is the rung after).
                start_epoch, start_offset = resil.selfheal.\
                    handle_rollback(
                        rb, args=args, step_mgr=step_mgr,
                        like=bundle_fn(state, 0,
                                       integrity='template'),
                        state=state,
                        dkfac=dkfac, sink=metrics_sink,
                        controller=selfheal_ctl,
                        kfac_sched=kfac_sched, checkpointer=step_ckpt,
                        verbose=is_main)
                epoch = start_epoch
                continue
            if args.precise_bn_batches > 0:
                # Precise-BN: eval with stats re-estimated at the current
                # weights; the training EWMA state is restored afterwards.
                import itertools
                recal = engine.precise_bn_recalibrate(
                    model, state.params, state.extra_vars,
                    launch.global_batches(
                        mesh,
                        itertools.islice(train_iter_fn(epoch),
                                         args.precise_bn_batches),
                        already_sharded=batches_local),
                    mesh, steps=bn_steps)
                train_extra, state.extra_vars = state.extra_vars, recal
            engine.evaluate(
                eval_step, state,
                launch.global_batches(mesh, val_iter_fn(),
                                      already_sharded=batches_local),
                log_writer=writer, verbose=is_main)
            if args.precise_bn_batches > 0:
                state.extra_vars = train_extra
            if kfac_sched:
                kfac_sched.step(epoch + 1)
            if (epoch + 1) % args.checkpoint_freq == 0 or \
                    epoch == args.epochs - 1:
                # force=: a cross-epoch self-heal rollback replays
                # epochs whose bundles already exist on disk; the
                # replayed save must overwrite, not crash (the step
                # checkpointer already saves with force for the same
                # reason).
                mgr.save(epoch, bundle_fn(state, 0), force=True)
            epoch += 1
    except resil.preemption.Preempted as p:
        # The step checkpoint is already durable (blocking save).
        step_ckpt.close()
        mgr.wait_until_finished()
        if metrics_sink is not None:
            metrics_sink.close()
        if rank_sink is not None:
            rank_sink.close()
        if heartbeat is not None:
            heartbeat.close()
        if is_main:
            print(f'preempted ({p.reason}) at global step '
                  f'{p.global_step}; checkpoint saved — exiting '
                  f'{resil.preemption.RELAUNCH_EXIT_CODE} for relaunch')
        return resil.preemption.RELAUNCH_EXIT_CODE
    step_ckpt.close()
    mgr.wait_until_finished()  # async saves: durable before exit
    if metrics_sink is not None:
        metrics_sink.close()
    if rank_sink is not None:
        rank_sink.close()
    if heartbeat is not None:
        heartbeat.close()
    if writer is not None:
        writer.flush()
    if is_main:
        print(f'total: {time.perf_counter() - t_start:.1f}s')
    return 0


if __name__ == '__main__':
    sys.exit(main())
