"""Microbenchmark: per-firing eigendecomposition cost on factor stacks.

Times ONE inverse-update firing's worth of decompositions over a
synthetic "trained-like" ResNet-32 factor set (the BASELINE.md north
star workload: many medium SPD matrices, bucketed by size), comparing

  - xla:   bucketed vmapped backend eigh (cold, data-dependent runtime)
  - warm:  ops.linalg.eigh_polish seeded with a mildly-rotated exact
           basis — the steady-state of eigh_method='auto' tracking
  - newton / cholesky: the damped-inverse paths (no eigenbasis), for
           the floor

Trained-like matters: XLA's TPU eigh runs ~5x longer on spread-spectrum
covariance factors than on near-identity ones (PERF.md §6), which is
exactly what EWMA factors become during training. Spectra here span
1e-4..tr with log-uniform spacing.

Run on the target chip:
    python benchmarks/eigh_methods.py [--repeats 5]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from distributed_kfac_pytorch_tpu.ops import linalg, pallas_kernels

# ResNet-32 / CIFAR-10 factor-size multiset (A: c*9+1 per conv + first
# conv 28 + linear 65; G: out-channels), as the bucketed eigen path sees
# it (preconditioner._size_buckets).
RESNET32_DIMS = ([28] + [145] * 11 + [289] * 10 + [577] * 10 + [65]
                 + [16] * 12 + [32] * 10 + [64] * 11 + [10])


def trained_like_stack(rng, dims):
    """{dim: (B, dim, dim) fp32 stack} with spread covariance spectra."""
    buckets = {}
    for dim in sorted(set(dims)):
        count = dims.count(dim)
        mats = []
        for _ in range(count):
            spec = np.geomspace(1e-4, 1.0, dim) * np.exp(
                rng.standard_normal(dim) * 0.3)
            q, _ = np.linalg.qr(rng.standard_normal((dim, dim)))
            mats.append((q * spec) @ q.T)
        buckets[dim] = jnp.asarray(np.stack(mats), jnp.float32)
    return buckets


def rand_rotation(rng, n, angle):
    """Random orthogonal rotation with spectral angle ``angle`` rad.

    ``expm(S)`` for a random skew-symmetric ``S`` rescaled so its
    largest rotation angle is exactly ``angle``. Canonical helper shared
    with tests/test_warm_eigh.py — keep the two call sites on this one
    implementation.
    """
    s = rng.standard_normal((n, n))
    s = (s - s.T) / 2
    w, v = np.linalg.eigh(1j * s)       # expm via eigh of Hermitian iS
    w = w * (angle / np.max(np.abs(w)))
    return np.real((v * np.exp(-1j * w)) @ v.conj().T)


def precond_rel_err(a, q, d, lam=1e-3, rng=None, exact_wv=None):
    """Relative error of applying ``(A + lam I)^-1`` via (q, d) vs exact.

    The metric K-FAC consumes: basis mixing inside eigenvalue clusters
    cancels here (the damping quotient is ~flat across a cluster), while
    genuine basis/eigenvalue error shows up directly. Canonical helper
    shared with tests/test_warm_eigh.py and benchmarks/middim_eigen.py.

    ``exact_wv``: optional precomputed ``(w, v) = np.linalg.eigh(a)``
    oracle — pass it when cold eighs at the bench's dims are exactly the
    expensive thing under study (middim_eigen); ``a`` is ignored then.
    """
    rng = rng or np.random.default_rng(7)
    dr, qr = exact_wv if exact_wv is not None else np.linalg.eigh(a)
    g = rng.standard_normal((qr.shape[0], 3))
    out = q @ ((q.T @ g) / (np.maximum(d, 0)[:, None] + lam))
    ref = qr @ ((qr.T @ g) / (np.maximum(dr, 0)[:, None] + lam))
    return float(np.linalg.norm(out - ref) / np.linalg.norm(ref))


def warm_bases(rng, buckets, angle=0.1):
    """Exact bases rotated by ``angle`` rad (spectral) — the tracked
    state one firing later. The rotation is normalized to a total
    *angle*, not a per-entry scale: per-firing eigenvector motion under
    EWMA drift is angle-bounded regardless of dimension."""
    out = {}
    for dim, stack in buckets.items():
        qs = []
        for m in np.asarray(stack):
            _, q = np.linalg.eigh(m)
            qs.append(q @ rand_rotation(rng, dim, angle))
        out[dim] = jnp.asarray(np.stack(qs), jnp.float32)
    return out


def _fetch_scalar(out):
    """Host-fetch one element — the only reliable completion barrier
    through the tunneled backend (per-call ``block_until_ready`` can
    acknowledge without executing; see bench.py's methodology notes
    and middim_eigen's recorded 0.04 ms "2304 eigh" artifact)."""
    leaf = jax.tree.leaves(out)[0]
    return float(leaf.reshape(-1)[0].real)


def time_fn(fn, args, repeats):
    """Min-of-repeats timing with a scalar-fetch window close.

    CAVEAT (recorded): repeats reuse identical inputs, so on the
    tunneled backend a repeat CAN be served from the execution-
    memoization cache and read near-zero; the scalar fetch closes the
    async-acknowledge hole but not that one. middim_eigen.time_variants
    (distinct inputs per repeat) is the fully hardened variant —
    prefer it for new benches; this helper keeps the rounds-3/4
    artifact methodology reproducible."""
    out = fn(*args)  # compile + warm
    _fetch_scalar(out)
    best = float('inf')
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn(*args)
        _fetch_scalar(out)
        best = min(best, time.perf_counter() - t0)
    return best * 1000.0, out


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--repeats', type=int, default=5)
    p.add_argument('--polish-iters', type=int, default=16)
    args = p.parse_args(argv)

    rng = np.random.default_rng(0)
    buckets = trained_like_stack(rng, RESNET32_DIMS)
    bases = warm_bases(rng, buckets)

    @jax.jit
    def run_xla(bk):
        return {d: linalg.batched_eigh(s, 'xla', clip=0.0)
                for d, s in bk.items()}

    @jax.jit
    def run_warm(bk, qs):
        return {d: linalg.batched_eigh(
            s, 'warm', clip=0.0, q_prev=qs[d],
            polish_iters=args.polish_iters) for d, s in bk.items()}

    @jax.jit
    def run_newton(bk):
        return {d: pallas_kernels.damped_inverse_stack(s, 0.003, 'newton')
                for d, s in bk.items()}

    @jax.jit
    def run_cholesky(bk):
        return {d: pallas_kernels.damped_inverse_stack(s, 0.003,
                                                       'cholesky')
                for d, s in bk.items()}

    results = {}
    results['xla_ms'], _ = time_fn(run_xla, (buckets,), args.repeats)
    results['warm_ms'], warm_out = time_fn(run_warm, (buckets, bases),
                                           args.repeats)
    results['newton_ms'], _ = time_fn(run_newton, (buckets,), args.repeats)
    results['cholesky_ms'], _ = time_fn(run_cholesky, (buckets,),
                                        args.repeats)

    # Accuracy of the warm firing (max preconditioning-op error).
    worst = 0.0
    for dim, stack in buckets.items():
        qs, ds = warm_out[dim]
        for i, m in enumerate(np.asarray(stack)):
            worst = max(worst, precond_rel_err(
                m, np.asarray(qs[i]), np.asarray(ds[i]), rng=rng))

    print(json.dumps({
        'workload': 'resnet32_factor_set_trained_like',
        'n_matrices': len(RESNET32_DIMS),
        'backend': jax.default_backend(),
        'unit': 'ms/firing',
        **{k: round(v, 3) for k, v in results.items()},
        'warm_speedup_vs_xla': round(results['xla_ms']
                                     / results['warm_ms'], 2),
        'warm_worst_precond_rel_err': float(f'{worst:.3g}'),
    }))


if __name__ == '__main__':
    main()
