"""Microbenchmark: K-FAC step time across factor-inversion methods.

Times the full jitted K-FAC + SGD training step on ResNet-32/CIFAR-10 at
the reference CIFAR cadence (factors every iter, inverses every 10 —
reference torch_cifar10_resnet.py:68-71) for each ``inverse_method``:

  - eigen:      the default eigen path (eigh_method='auto': warm-start
                matmul-only basis polish, ops.linalg.eigh_polish)
  - eigen-xla:  bucketed vmapped backend eigh every firing (the
                reference-style cold decomposition; data-dependent
                runtime on TPU, PERF.md §6)
  - cholesky:   damped Cholesky inverse (reference --use-inv-kfac)
  - newton:     matmul-only Newton-Schulz (Pallas VMEM-resident on TPU)

(For the plain-SGD floor / overhead ratio, see bench.py.) Run on the
target chip:
    python benchmarks/inverse_methods.py [--batch-size 128] [--iters 50]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from distributed_kfac_pytorch_tpu import KFAC
from distributed_kfac_pytorch_tpu.models import cifar_resnet


def build_kfac_step(model, x, y, method):
    inverse_method, _, eigh = method.partition('-')
    kfac = KFAC(model, factor_update_freq=1, inv_update_freq=10,
                damping=0.003, lr=0.1, inverse_method=inverse_method,
                eigh_method=eigh or 'auto')
    variables, kstate = kfac.init(jax.random.PRNGKey(0), x)
    params = variables['params']
    extra = {k: v for k, v in variables.items() if k != 'params'}
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, kstate, extra):
        loss, _, grads, captures, updated = kfac.capture.loss_and_grads(
            lambda out: optax.softmax_cross_entropy_with_integer_labels(
                out, y).mean(),
            params, x, extra_vars=extra, mutable_cols=('batch_stats',))
        precond, kstate = kfac.step(kstate, grads, captures)
        updates, opt_state = tx.update(precond, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, kstate, {**extra, **updated}, loss

    return step, (params, opt_state, kstate, extra)


def time_step(step, state, iters, warmup=12):
    for _ in range(warmup):
        *state, loss = step(*state)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        *state, loss = step(*state)
    jax.block_until_ready(loss)
    return (time.perf_counter() - t0) / iters * 1000


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--batch-size', type=int, default=128)
    p.add_argument('--iters', type=int, default=50)
    p.add_argument('--model', default='resnet32')
    args = p.parse_args(argv)

    model = cifar_resnet.get_model(args.model)
    # Random data, never constants: constant inputs degenerate batchnorm
    # (zero variance -> NaNs) and execute pathologically slowly on the
    # tunneled TPU runtime, poisoning the measurement.
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (args.batch_size, 32, 32, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (args.batch_size,),
                           0, 10)

    results = {}
    for method in ('eigen', 'eigen-xla', 'cholesky', 'newton'):
        step, state = build_kfac_step(model, x, y, method)
        results[method] = round(time_step(step, state, args.iters), 3)
    print(json.dumps({'model': args.model, 'batch': args.batch_size,
                      'unit': 'ms/iter', **results}))


if __name__ == '__main__':
    main()
