"""HBM roofline audit of the CIFAR conv A-factor phase (round 4).

The round-3 claim "the slices path sits at the materialized-patch HBM
roofline" was asserted from a per-layer decomposition, never
demonstrated as achieved-bytes/s (VERDICT r3 Weak #1). This tool
measures, with the microbench's hoist-proof chained methodology
(value-dependent input nudge per iteration, null-baseline subtraction,
floor-gated timing — see conv_a_microbench.build_runner):

  copy      read+write of an N-byte tensor -> achieved HBM bandwidth
            (the empirical peak the roofline is computed against);
  cov       the covariance contraction alone on a pre-materialized
            patch tensor (its cost is dominated by the patch READ);
  full      the production A-factor call (extraction + covariance,
            fused however XLA chooses).

(An extraction-alone leg is not measurable: with anything less than a
full consumer XLA dead-code-eliminates the unmaterialized patch lanes,
and a full consumer IS a covariance-class read — measured and
discarded in round 4.)

Roofline logic: ``implied_gb_s`` is the full leg's materialization
traffic (patch write + patch read + input read) over its wall time; if
it approaches the achieved copy bandwidth, the phase is memory-bound
at the materialization traffic and further gains require never
materializing patches (the measured negatives: fused Pallas kernel,
crosscov; and 'pairs', which wins only at d > 640). ``full_vs_floor``
< 1 means XLA avoided part of that traffic (partial fusion).

    python benchmarks/factor_roofline.py [--inner 30]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench as B  # noqa: E402
from distributed_kfac_pytorch_tpu.ops import factors as F  # noqa: E402

SHAPES = [
    ('cifar_stage1_c16_32x32', 512, 32, 32, 16),
    ('cifar_stage2_c32_16x16', 512, 16, 16, 32),
    ('cifar_stage3_c64_8x8', 512, 8, 8, 64),
]


def chained(body_fn, carry0, inner):
    """Time a carry-chained scan of ``body_fn`` (hoist-proof: the carry
    is nudged by a value computed FROM each iteration's result, so no
    iteration is loop-invariant)."""
    @jax.jit
    def run(carry):
        carry, out = jax.lax.scan(body_fn, carry, None, length=inner)
        return carry, out[-1]

    return B.time_chained(run, carry0, inner)


def null_leg(x0, inner):
    def body(x, _):
        probe = jnp.float32(1e-9) * x.reshape(-1)[0].astype(jnp.float32)
        return x * (1.0 + 1e-6 * probe.astype(x.dtype)), probe
    return chained(body, x0, inner)


def copy_leg(x0, inner):
    def body(x, _):
        y = x + jnp.asarray(1.0, x.dtype)           # read + write
        probe = y.reshape(-1)[0].astype(jnp.float32)
        return y * (1.0 + 1e-6 * probe.astype(x.dtype) * 0), probe
    return chained(body, x0, inner)


def cov_leg(p0, inner):
    def body(p, _):
        cov = F.get_cov(p, scale=p.shape[0])
        probe = cov[0, 0]
        return p * (1.0 + 1e-6 * probe.astype(p.dtype)), probe
    return chained(body, p0, inner)


def full_leg(x0, inner, kernel):
    os.environ['KFAC_CONV_PATCH_IMPL'] = 'slices'
    try:
        def body(x, _):
            a = F.conv2d_a_factor(x, kernel, (1, 1), 'SAME', True)
            return x * (1.0 + 1e-6 * a[0, 0].astype(x.dtype)), a[0, 0]
        return chained(body, x0, inner)
    finally:
        os.environ.pop('KFAC_CONV_PATCH_IMPL', None)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--inner', type=int, default=30)
    args = p.parse_args(argv)
    kernel = (3, 3)

    # Empirical bandwidth: read+write a ~150 MB bf16 tensor.
    big = jax.random.normal(jax.random.PRNGKey(9),
                            (512, 32, 32, 144)).astype(jnp.bfloat16)
    base_big = null_leg(big, args.inner)
    ms_copy = max(copy_leg(big, args.inner) - base_big, 1e-6)
    gbs = big.size * 2 * 2 / ms_copy * 1e3 / 1e9
    print(json.dumps({'leg': 'copy', 'mbytes': round(big.size * 2 / 1e6),
                      'ms': round(ms_copy, 3),
                      'achieved_gb_s': round(gbs, 1)}), flush=True)

    for label, b, h, w, c in SHAPES:
        x0 = jax.random.normal(jax.random.PRNGKey(0),
                               (b, h, w, c)).astype(jnp.bfloat16)
        d = kernel[0] * kernel[1] * c
        rows = b * h * w
        patch_mb = rows * d * 2 / 1e6
        input_mb = b * h * w * c * 2 / 1e6
        base = null_leg(x0, args.inner)
        p0 = jax.random.normal(jax.random.PRNGKey(1),
                               (rows, d)).astype(jnp.bfloat16)
        base_p = null_leg(p0, args.inner)
        ms_cov = max(cov_leg(p0, args.inner) - base_p, 0.0)
        ms_full = max(full_leg(x0, args.inner, kernel) - base, 1e-6)
        # Materialization roofline at the ACHIEVED copy bandwidth:
        # patch write (extract) + patch read (cov operand) + input read.
        mat_mb = 2 * patch_mb + input_mb
        floor_ms = mat_mb * 1e6 / (gbs * 1e9) * 1e3
        implied = mat_mb * 1e6 / (ms_full * 1e-3) / 1e9
        print(json.dumps({
            'shape': label, 'patch_mb': round(patch_mb, 1),
            'cov_ms': round(ms_cov, 3),
            'full_ms': round(ms_full, 3),
            'materialization_floor_ms_at_achieved_bw':
                round(floor_ms, 3),
            'full_vs_floor': round(ms_full / max(floor_ms, 1e-9), 2),
            'implied_gb_s': round(implied, 1),
            'implied_vs_achieved_copy_bw': round(implied / gbs, 2),
        }), flush=True)


if __name__ == '__main__':
    main()
