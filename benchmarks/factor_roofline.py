"""HBM roofline audit of the CIFAR conv A-factor phase (round 4).

The round-3 claim "the slices path sits at the materialized-patch HBM
roofline" was asserted from a per-layer decomposition, never
demonstrated as achieved-bytes/s (VERDICT r3 Weak #1). This tool
measures, with the microbench's hoist-proof chained methodology
(value-dependent input nudge per iteration, null-baseline subtraction,
floor-gated timing — see conv_a_microbench.build_runner):

  copy      read+write of an N-byte tensor -> achieved HBM bandwidth
            (the empirical peak the roofline is computed against);
  cov       the covariance contraction alone on a pre-materialized
            patch tensor (its cost is dominated by the patch READ);
  full      the production A-factor call (extraction + covariance,
            fused however XLA chooses).

(An extraction-alone leg is not measurable: with anything less than a
full consumer XLA dead-code-eliminates the unmaterialized patch lanes,
and a full consumer IS a covariance-class read — measured and
discarded in round 4.)

Roofline logic: ``implied_gb_s`` is the full leg's materialization
traffic (patch write + patch read + input read) over its wall time; if
it approaches the achieved copy bandwidth, the phase is memory-bound
at the materialization traffic and further gains require never
materializing patches (the measured negatives: fused Pallas kernel,
crosscov; and 'pairs', which wins only at d > 640). ``full_vs_floor``
< 1 means XLA avoided part of that traffic (partial fusion).

r21 adds the fused hot-path legs (``--fused-inner`` chained
iterations each):

  factor_ema   stock ``get_cov`` + ``update_running_avg`` vs the
               symmetry-packed fused contraction+EMA Pallas kernel on
               linear-factor shapes — the fused kernel round-trips only
               the d(d+1)/2 triangle of the EMA state through HBM
               instead of two dense d^2 tensors;
  precond      stock vmapped ``precondition_dispatch`` + separate v·g
               reduction vs the fused bucket kernel with the KL-clip
               epilogue on a same-shape eigen bucket stack.

Both report stock/fused ms and the implied bytes/s of each leg's
traffic model against the achieved copy bandwidth (on non-TPU backends
the fused legs run the kernel body in interpret mode: parity
provenance only — the ms there measure the interpreter, not Mosaic;
rerun on TPU for decision-grade numbers).

    python benchmarks/factor_roofline.py [--inner 30]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench as B  # noqa: E402
from distributed_kfac_pytorch_tpu.ops import factors as F  # noqa: E402
from distributed_kfac_pytorch_tpu.ops import (  # noqa: E402
    linalg,
    pallas_kernels,
)

SHAPES = [
    ('cifar_stage1_c16_32x32', 512, 32, 32, 16),
    ('cifar_stage2_c32_16x16', 512, 16, 16, 32),
    ('cifar_stage3_c64_8x8', 512, 8, 8, 64),
]

#: (label, rows, d) linear-factor contraction shapes for the r21 fused
#: EMA legs — the LM ladder's collapsed (batch*seq, d) activations.
EMA_SHAPES = [
    ('lm_d256', 4096, 256),
    ('lm_d512', 4096, 512),
]

#: (label, stack, g_dim, a_dim) same-shape eigen bucket stacks for the
#: r21 fused precondition legs.
PRECOND_SHAPES = [
    ('bucket_s4_256x256', 4, 256, 256),
    ('bucket_s8_128x128', 8, 128, 128),
]


def chained(body_fn, carry0, inner):
    """Time a carry-chained scan of ``body_fn`` (hoist-proof: the carry
    is nudged by a value computed FROM each iteration's result, so no
    iteration is loop-invariant)."""
    @jax.jit
    def run(carry):
        carry, out = jax.lax.scan(body_fn, carry, None, length=inner)
        return carry, out[-1]

    return B.time_chained(run, carry0, inner)


def null_leg(x0, inner):
    def body(x, _):
        probe = jnp.float32(1e-9) * x.reshape(-1)[0].astype(jnp.float32)
        return x * (1.0 + 1e-6 * probe.astype(x.dtype)), probe
    return chained(body, x0, inner)


def copy_leg(x0, inner):
    def body(x, _):
        y = x + jnp.asarray(1.0, x.dtype)           # read + write
        probe = y.reshape(-1)[0].astype(jnp.float32)
        return y * (1.0 + 1e-6 * probe.astype(x.dtype) * 0), probe
    return chained(body, x0, inner)


def cov_leg(p0, inner):
    def body(p, _):
        cov = F.get_cov(p, scale=p.shape[0])
        probe = cov[0, 0]
        return p * (1.0 + 1e-6 * probe.astype(p.dtype)), probe
    return chained(body, p0, inner)


def full_leg(x0, inner, kernel):
    os.environ['KFAC_CONV_PATCH_IMPL'] = 'slices'
    try:
        def body(x, _):
            a = F.conv2d_a_factor(x, kernel, (1, 1), 'SAME', True)
            return x * (1.0 + 1e-6 * a[0, 0].astype(x.dtype)), a[0, 0]
        return chained(body, x0, inner)
    finally:
        os.environ.pop('KFAC_CONV_PATCH_IMPL', None)


def ema_leg(x0, old0, inner, fused, interpret):
    def body(carry, _):
        x, old = carry
        if fused:
            new = pallas_kernels.fused_factor_ema(
                x, old, 0.95, interpret=interpret)
        else:
            new = F.update_running_avg(F.get_cov(x), old, 0.95)
        probe = new[0, 0]
        return (x * (1.0 + 1e-6 * probe.astype(x.dtype)), new), probe
    return chained(body, (x0, old0), inner)


def precond_leg(g0, entry, inner, fused, interpret):
    def body(g, _):
        if fused:
            v, vg = pallas_kernels.fused_bucket_precondition(
                g, entry, 0.003, interpret=interpret)
        else:
            v = jax.vmap(lambda gm, e: linalg.precondition_dispatch(
                gm, e, 0.003))(g, entry)
            vg = jnp.sum(v * g, axis=(1, 2))
        probe = vg[0]
        return g * (1.0 + 1e-6 * probe.astype(g.dtype)), probe
    return chained(body, g0, inner)


def fused_rows(inner, gbs):
    """The r21 fused-vs-stock A/B rows (see module docstring)."""
    interpret = jax.default_backend() != 'tpu'
    for label, rows, d in EMA_SHAPES:
        x0 = jax.random.normal(jax.random.PRNGKey(2),
                               (rows, d), jnp.float32)
        old0 = jnp.eye(d, dtype=jnp.float32)
        base = null_leg(x0, inner)
        ms_stock = max(ema_leg(x0, old0, inner, False, interpret)
                       - base, 1e-6)
        ms_fused = max(ema_leg(x0, old0, inner, True, interpret)
                       - base, 1e-6)
        # Traffic models: both read x (rows*d); the stock blend
        # round-trips two dense d^2 fp32 tensors (old read + new
        # write, with the cov intermediate ideally fused), the packed
        # kernel two d(d+1)/2 triangles.
        x_mb = rows * d * 4 / 1e6
        dense_mb = x_mb + 2 * d * d * 4 / 1e6
        packed_mb = x_mb + 2 * (d * (d + 1) // 2) * 4 / 1e6
        print(json.dumps({
            'leg': 'factor_ema', 'shape': label,
            'interpret': interpret,
            'stock_ms': round(ms_stock, 3),
            'fused_ms': round(ms_fused, 3),
            'fused_speedup': round(ms_stock / ms_fused, 2),
            'stock_implied_gb_s': round(
                dense_mb * 1e6 / (ms_stock * 1e-3) / 1e9, 1),
            'fused_implied_gb_s': round(
                packed_mb * 1e6 / (ms_fused * 1e-3) / 1e9, 1),
            'achieved_copy_gb_s': round(gbs, 1),
        }), flush=True)
    for label, s, g_dim, a_dim in PRECOND_SHAPES:
        rng = jax.random.PRNGKey(3)
        g0 = jax.random.normal(rng, (s, g_dim, a_dim), jnp.float32)
        qa = jnp.linalg.qr(jax.random.normal(
            jax.random.PRNGKey(4), (s, a_dim, a_dim)))[0]
        qg = jnp.linalg.qr(jax.random.normal(
            jax.random.PRNGKey(5), (s, g_dim, g_dim)))[0]
        entry = {
            'QA': qa.astype(jnp.float32),
            'dA': jnp.abs(jax.random.normal(
                jax.random.PRNGKey(6), (s, a_dim))) + 0.1,
            'QG': qg.astype(jnp.float32),
            'dG': jnp.abs(jax.random.normal(
                jax.random.PRNGKey(7), (s, g_dim))) + 0.1,
        }
        base = null_leg(g0, inner)
        ms_stock = max(precond_leg(g0, entry, inner, False, interpret)
                       - base, 1e-6)
        ms_fused = max(precond_leg(g0, entry, inner, True, interpret)
                       - base, 1e-6)
        print(json.dumps({
            'leg': 'precond', 'shape': label,
            'interpret': interpret,
            'stock_ms': round(ms_stock, 3),
            'fused_ms': round(ms_fused, 3),
            'fused_speedup': round(ms_stock / ms_fused, 2),
        }), flush=True)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--inner', type=int, default=30)
    p.add_argument('--fused-inner', type=int, default=None,
                   help='chained iterations for the r21 fused legs '
                        '(default: --inner)')
    p.add_argument('--skip-fused', action='store_true',
                   help='skip the r21 fused A/B legs')
    args = p.parse_args(argv)
    kernel = (3, 3)

    # Empirical bandwidth: read+write a ~150 MB bf16 tensor.
    big = jax.random.normal(jax.random.PRNGKey(9),
                            (512, 32, 32, 144)).astype(jnp.bfloat16)
    base_big = null_leg(big, args.inner)
    ms_copy = max(copy_leg(big, args.inner) - base_big, 1e-6)
    gbs = big.size * 2 * 2 / ms_copy * 1e3 / 1e9
    print(json.dumps({'leg': 'copy', 'mbytes': round(big.size * 2 / 1e6),
                      'ms': round(ms_copy, 3),
                      'achieved_gb_s': round(gbs, 1)}), flush=True)

    for label, b, h, w, c in SHAPES:
        x0 = jax.random.normal(jax.random.PRNGKey(0),
                               (b, h, w, c)).astype(jnp.bfloat16)
        d = kernel[0] * kernel[1] * c
        rows = b * h * w
        patch_mb = rows * d * 2 / 1e6
        input_mb = b * h * w * c * 2 / 1e6
        base = null_leg(x0, args.inner)
        p0 = jax.random.normal(jax.random.PRNGKey(1),
                               (rows, d)).astype(jnp.bfloat16)
        base_p = null_leg(p0, args.inner)
        ms_cov = max(cov_leg(p0, args.inner) - base_p, 0.0)
        ms_full = max(full_leg(x0, args.inner, kernel) - base, 1e-6)
        # Materialization roofline at the ACHIEVED copy bandwidth:
        # patch write (extract) + patch read (cov operand) + input read.
        mat_mb = 2 * patch_mb + input_mb
        floor_ms = mat_mb * 1e6 / (gbs * 1e9) * 1e3
        implied = mat_mb * 1e6 / (ms_full * 1e-3) / 1e9
        print(json.dumps({
            'shape': label, 'patch_mb': round(patch_mb, 1),
            'cov_ms': round(ms_cov, 3),
            'full_ms': round(ms_full, 3),
            'materialization_floor_ms_at_achieved_bw':
                round(floor_ms, 3),
            'full_vs_floor': round(ms_full / max(floor_ms, 1e-9), 2),
            'implied_gb_s': round(implied, 1),
            'implied_vs_achieved_copy_bw': round(implied / gbs, 2),
        }), flush=True)

    if not args.skip_fused:
        fused_rows(args.fused_inner or args.inner, gbs)


if __name__ == '__main__':
    main()
