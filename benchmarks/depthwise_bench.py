"""Measured depthwise-model bench: MobileNetV1 under grouped-conv K-FAC.

BEYOND the reference (VERDICT r4 #6): its registry has no conv variant
for ``feature_group_count != 1`` (``kfac/layers/__init__.py:13-36``),
so on MobileNet-class models every depthwise layer falls back to plain
gradients there. Here the 13 depthwise convs carry per-group
block-diagonal factors (kind ``conv2d_grouped``), and this bench
records what that path costs on a real chip.

Cumulative phases (step_breakdown methodology — scanned loop, chained
carries, median-of-repeats):

  sgd       plain SGD step (fwd+bwd+momentum)
  precond   + capture + preconditioning with frozen inverses + KL clip
  factors   + factor EWMA every iter (incl. the per-group block factors)
  full      + amortized inverse firing every ``inv_freq`` iters

    python benchmarks/depthwise_bench.py [--iters 30] [--batch 64]
        [--image 176] [--out DEPTHWISE_r05.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import functools

import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench as B  # noqa: E402  (repo root: the timing methodology)
from distributed_kfac_pytorch_tpu import KFAC
from distributed_kfac_pytorch_tpu.models import mobilenet
from distributed_kfac_pytorch_tpu.utils import enable_compilation_cache


def build(kfac, variables, kstate, model, x, y, inv_freq, n_iters, mode):
    params = variables['params']
    extra = {k: v for k, v in variables.items() if k != 'params'}
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    def loss(out):
        return B.loss_fn(out, y)

    def make_body(factor_update, inv_update):
        def body(carry, _):
            params, opt_state, kstate, extra = carry
            loss_v, _, grads, captures, updated = (
                kfac.capture.loss_and_grads(
                    loss, params, x, extra_vars=extra,
                    mutable_cols=('batch_stats',)))
            g, kstate2 = kfac.step(kstate, grads, captures,
                                   factor_update=factor_update,
                                   inv_update=inv_update)
            updates, opt_state = tx.update(g, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, kstate2, {**extra, **updated}), loss_v
        return body

    if mode == 'sgd':
        def sgd_body(carry, _):
            params, opt_state, extra = carry

            def wrapped(p):
                out, updated = model.apply({'params': p, **extra}, x,
                                           mutable=['batch_stats'])
                return loss(out), updated
            (l, updated), grads = jax.value_and_grad(
                wrapped, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, {**extra, **updated}), l

        @jax.jit
        def run(carry):
            carry, losses = jax.lax.scan(sgd_body, carry, None,
                                         length=n_iters)
            return carry, losses[-1]
        return run, (params, opt_state, extra)

    if mode == 'precond':
        body = make_body(False, False)
    elif mode == 'factors':
        body = make_body(True, False)
    elif mode == 'full':
        inv_body = make_body(True, True)
        plain_body = make_body(True, False)

        def block(carry, _):
            carry, _ = inv_body(carry, None)
            carry, ls = jax.lax.scan(plain_body, carry, None,
                                     length=inv_freq - 1)
            return carry, ls[-1]

        @jax.jit
        def run(carry):
            carry, losses = jax.lax.scan(block, carry, None,
                                         length=n_iters // inv_freq)
            return carry, losses[-1]
        return run, (params, opt_state, kstate, extra)
    else:
        raise ValueError(mode)

    # Donated carry — mirror of flagship_resnet50.phase_step_leg
    # (time_chained chains carry = run(carry); the old carry is dead).
    # Unlike the flagship (one subprocess per leg), every mode here
    # shares one process and one (variables, kstate), so donate a
    # fresh device COPY — donating the originals would delete them
    # for the next mode's leg.
    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(carry):
        carry, losses = jax.lax.scan(body, carry, None, length=n_iters)
        return carry, losses[-1]
    carry0 = jax.tree.map(jnp.copy, (params, opt_state, kstate, extra))
    return run, carry0


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--iters', type=int, default=30)
    p.add_argument('--batch', type=int, default=64)
    p.add_argument('--image', type=int, default=176)
    p.add_argument('--width-mult', type=float, default=1.0)
    p.add_argument('--model-dtype', default='bf16',
                   choices=['fp32', 'bf16'])
    p.add_argument('--out', default='DEPTHWISE_r05.json')
    args = p.parse_args(argv)
    enable_compilation_cache()

    on_tpu = jax.default_backend() == 'tpu'
    if not on_tpu:  # CPU shake-out config
        args.batch, args.image, args.width_mult = 4, 64, 0.25
    dt = jnp.bfloat16 if args.model_dtype == 'bf16' else jnp.float32
    model = mobilenet.get_model(dtype=dt, width_mult=args.width_mult)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (args.batch, args.image, args.image, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (args.batch,), 0, 1000)
    inv_freq = 10
    n_iters = (args.iters // inv_freq) * inv_freq or inv_freq

    kfac = KFAC(model, factor_update_freq=1, inv_update_freq=inv_freq,
                damping=0.003, lr=0.1)
    variables, kstate = kfac.init(jax.random.PRNGKey(0), x)
    n_grouped = sum(s.kind == 'conv2d_grouped'
                    for s in kfac.specs.values())
    floor_ms = B.flops_floor_ms(kfac, variables, x, y,
                                mutable_cols=('batch_stats',))

    rows = {}
    for mode in ('sgd', 'precond', 'factors', 'full'):
        run, carry = build(kfac, variables, kstate, model, x, y,
                           inv_freq, n_iters, mode)
        ms = B.time_chained(run, carry, n_iters, floor_ms=floor_ms,
                            leg=mode)
        rows[mode] = round(ms, 2)
        print(json.dumps({'phase': mode, 'ms_per_iter': rows[mode]}),
              flush=True)

    out = {
        'workload': f'mobilenetv1_{args.width_mult}x_{args.image}px_'
                    f'b{args.batch}_{args.model_dtype}',
        'backend': jax.default_backend(),
        'n_grouped_layers': n_grouped,
        'unit': 'ms/iter',
        'phases': rows,
        'deltas': {
            'capture_precond_cost': round(rows['precond'] - rows['sgd'], 2),
            'factor_cost': round(rows['factors'] - rows['precond'], 2),
            'inverse_amortized_cost': round(rows['full'] - rows['factors'],
                                            2),
        },
        'vs_sgd': {
            'every_iter_factors': round(rows['factors'] / rows['sgd'], 3),
            'cifar_cadence_full': round(rows['full'] / rows['sgd'], 3),
        },
        'note': 'all 13 depthwise convs preconditioned via per-group '
                'block factors; the reference cannot precondition any '
                'of them (registry gap, kfac/layers/__init__.py:13-36)',
    }
    with open(args.out, 'w') as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out), flush=True)


if __name__ == '__main__':
    main()
