"""KAISA comm-strategy decision model: which comm_method at which scale?

The reference exposes COMM_OPT / MEM_OPT / HYBRID_OPT and leaves the
choice to the user (kfac/preconditioner.py:235-259); the KAISA paper
frames it as a memory/communication tradeoff but publishes no decision
rule. SURVEY.md §7 flags the open question for TPU: on fast ICI, does
sharding inverse state (MEM/HYBRID) ever *pay*, or does the gather /
psum traffic cost more than the memory it saves?

This model answers it quantitatively from this framework's OWN
communication structure (parallel/distributed.py), calibrated with
on-chip measured leg times (FLAGSHIP_r04/r05) and parameterized by the
interconnect. Volumes per device per step, for world W split as
R inverse groups x C grad workers (COMM_OPT: R=1, C=W; MEM_OPT: R=W,
C=1; HYBRID f=C/W):

  data-parallel grad pmean    2 * (W-1)/W * B_params      every step
  factor pmean                2 * (W-1)/W * B_factors     every 1/Tf
  inverse all_gather (gw)     (C-1)/C * B_inv / R         every 1/Ti
  precond-grad psum (ig)      2 * (R-1)/R * B_grads       every step

(ring-collective per-device wire bytes; B_inv/R because each inverse
group's stack holds only its own layers' inverses — layers are
LPT-balanced over rows, assign_work()). Compute per device per step:

  fwd/bwd + every-iter K-FAC   measured leg (cadence-composed)
  decompositions               T_fire / (R*C) / Ti   (the bucket stack
                               is row- AND column-sharded: every device
                               decomposes slots_per_col slots)
  precondition matmuls         T_precond / R          (row-sharded,
                               shard_precond_compute=True)

So in THIS design the decomposition FLOPs shard over the full mesh for
every strategy — the strategies differ only in wire bytes and in
inverse-state memory per device (COMM_OPT replicates all inverse
stacks within a row of size W; MEM_OPT stores 1/W per device). That is
exactly the KAISA tradeoff, with the reference's "grad worker
fraction" reinterpreted for SPMD.

Usage:
    python benchmarks/kaisa_decision_model.py \
        [--ici-gbps 40] [--dcn-gbps 3] [--out KAISA_DECISION.json]

The bandwidth defaults are PARAMETERS, not measurements (one real chip
here — no ICI to measure): 40 GB/s effective per-device allreduce
bandwidth is a conservative public v4-class ICI figure; 3 GB/s is a
DCN-class figure consistent with the COMM_MULTIHOST.json gloo ordering.
Re-run with your pod's measured numbers to recompute the verdict.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))


def factor_set(which: str):
    """Per-layer (a_dim, g_dim) + param bytes for a tracked workload.

    Dims derive from kernel shapes only (spatial-independent), so the
    registration trace runs at a small image / short sequence.
    """
    import jax
    import jax.numpy as jnp

    from distributed_kfac_pytorch_tpu import KFAC

    if which == 'resnet50':
        from distributed_kfac_pytorch_tpu.models import imagenet_resnet
        model = imagenet_resnet.get_model('resnet50')
        kfac = KFAC(model)
        variables, _ = kfac.init(jax.random.PRNGKey(0),
                                 jnp.zeros((2, 64, 64, 3)))
    elif which == 'lm':
        from distributed_kfac_pytorch_tpu.models import transformer_lm
        model = transformer_lm.get_model(vocab_size=32768, size='xl',
                                         max_len=1024, dropout=0.0)
        kfac = KFAC(model)
        variables, _ = kfac.init(
            jax.random.PRNGKey(0),
            jnp.zeros((2, 64), jnp.int32), train=False)
    else:
        raise ValueError(which)
    import distributed_kfac_pytorch_tpu.layers.base as L

    shapes = {}
    for name, spec in kfac.specs.items():
        node = variables['params']
        for part in spec.path:
            node = node[part]
        shapes[name] = (L.factor_shapes(spec, node), spec.kind)
    n_params = sum(x.size for x in jax.tree.leaves(variables['params']))
    return kfac, shapes, n_params


def volumes(kfac, shapes, n_params, *, fdt_bytes=4, idt_bytes=4):
    """Static byte/flop totals the strategy costs scale from."""
    B_params = n_params * 4
    B_factors = B_grads = 0
    B_inv = 0
    decomp_flops = 0
    precond_flops_per_item = 0
    for name, ((a, g), kind) in shapes.items():
        if kind == 'embedding':
            # Diagonal A (vector factor + elementwise inverse); G is a
            # dense g x g factor with a full decomposed inverse like any
            # other layer (preconditioner.init_state).
            B_factors += (a + g * g) * fdt_bytes
            B_inv += a * idt_bytes
            dims = (g,)
            B_grads += a * g * 4
            precond_flops_per_item += 2 * (g * g * a + a * g)
        else:
            B_factors += (a * a + g * g) * fdt_bytes
            B_grads += a * g * 4
            dims = (a, g)
            # Precondition: G_side @ grad @ A_side twice-ish.
            precond_flops_per_item += 2 * (g * g * a + a * a * g)
        for d in dims:
            method = kfac.method_for_dim(d)
            if method == 'eigen':
                # Q + eigenvalues.
                B_inv += (d * d + d) * idt_bytes
                decomp_flops += 8 * d ** 3  # polish-iter matmul budget
            else:
                B_inv += d * d * idt_bytes
                decomp_flops += d ** 3 / 3  # Cholesky
    return {'B_params': B_params, 'B_factors': B_factors,
            'B_inv': B_inv, 'B_grads': B_grads,
            'decomp_flops': decomp_flops,
            'precond_flops': precond_flops_per_item}


def strategy_cost(vol, W, C, Tf, Ti, *, gbps, base_ms, factor_extra_ms,
                  fire_ms_1dev, precond_ms_1dev):
    """Predicted ms/step/device for a (W, C) layout at cadence (Tf, Ti).

    base_ms: measured single-chip non-factor K-FAC step (fwd/bwd +
    precondition replicated + KL clip). The replicated precondition in
    that leg is swapped for the row-sharded share.
    """
    R = W // C
    bw = gbps * 1e9
    comm_s = 2 * (W - 1) / W * vol['B_params'] / bw
    comm_s += 2 * (W - 1) / W * vol['B_factors'] / bw / Tf
    if C > 1:
        comm_s += (C - 1) / C * vol['B_inv'] / R / bw / Ti
    if R > 1:
        comm_s += 2 * (R - 1) / R * vol['B_grads'] / bw
    # Compute: measured legs, resharded.
    fire_ms = fire_ms_1dev / (R * C) / Ti
    # precond leg was measured replicated (R=1 equivalent): sharing
    # over R rows saves (1 - 1/R) of it.
    precond_ms = precond_ms_1dev * (1 / R - 1)
    total = (base_ms + factor_extra_ms / Tf + fire_ms + precond_ms
             + comm_s * 1e3)
    return {'ms_per_step': round(total, 3),
            'comm_ms': round(comm_s * 1e3, 3),
            'fire_ms_amortized': round(fire_ms, 3),
            'inv_bytes_per_dev': int(vol['B_inv'] / R)}


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--ici-gbps', type=float, default=40.0,
                   help='effective per-device allreduce bandwidth '
                        '(PARAMETER, not a measurement)')
    p.add_argument('--dcn-gbps', type=float, default=3.0)
    p.add_argument('--workload', default='resnet50',
                   choices=['resnet50', 'lm'])
    # Measured single-chip legs (defaults: FLAGSHIP_r04 224px b64 bf16
    # session 'r4-gated-capture'; override with a newer session's).
    p.add_argument('--base-ms', type=float, default=31.31,
                   help='measured non-factor K-FAC step ms (nofactor '
                        'leg)')
    p.add_argument('--factor-extra-ms', type=float, default=23.84,
                   help='measured factor-step premium over the '
                        'non-factor step')
    p.add_argument('--fire-ms', type=float, default=136.9,
                   help="measured single-chip 'auto' inverse firing ms")
    p.add_argument('--precond-ms', type=float, default=2.0,
                   help='measured precondition+clip premium (the '
                        'replicated share a row-sharded layout divides)')
    p.add_argument('--out', default='KAISA_DECISION.json')
    args = p.parse_args(argv)

    kfac, shapes, n_params = factor_set(args.workload)
    vol = volumes(kfac, shapes, n_params)

    cadences = {'imagenet_default_f10_i100': (10, 100),
                'production_f50_i500': (50, 500)}
    worlds = [8, 16, 32, 64, 256]
    rows = []
    for W in worlds:
        for label, gbps in (('ici', args.ici_gbps),
                            ('dcn', args.dcn_gbps)):
            for cad_name, (Tf, Ti) in cadences.items():
                per = {}
                layouts = {'comm_opt(C=W)': W, 'hybrid(C=W/2)': W // 2,
                           'hybrid(C=W/4)': max(W // 4, 1),
                           'mem_opt(C=1)': 1}
                for sname, C in layouts.items():
                    if C < 1 or W % C:
                        continue
                    per[sname] = strategy_cost(
                        vol, W, C, Tf, Ti, gbps=gbps,
                        base_ms=args.base_ms,
                        factor_extra_ms=args.factor_extra_ms,
                        fire_ms_1dev=args.fire_ms,
                        precond_ms_1dev=args.precond_ms)
                best = min(per, key=lambda k: per[k]['ms_per_step'])
                rows.append({'world': W, 'link': label, 'gbps': gbps,
                             'cadence': cad_name, 'best': best,
                             'strategies': per})

    result = {
        'workload': args.workload,
        'n_layers': len(shapes),
        'n_params': n_params,
        'volumes_bytes': {k: int(v) for k, v in vol.items()
                          if k.startswith('B_')},
        'measured_leg_inputs': {
            'base_ms': args.base_ms,
            'factor_extra_ms': args.factor_extra_ms,
            'fire_ms_1dev': args.fire_ms,
            'precond_ms_1dev': args.precond_ms},
        'bandwidth_parameters_note':
            'ici/dcn GB/s are PARAMETERS (no multi-chip interconnect '
            'exists in this environment); re-run with measured pod '
            'numbers to recompute',
        'model': 'see benchmarks/kaisa_decision_model.py docstring',
        'rows': rows,
    }
    with open(args.out, 'w') as f:
        json.dump(result, f, indent=1)

    # Human-readable verdict table.
    print(f'workload={args.workload} layers={len(shapes)} '
          f'params={n_params/1e6:.1f}M')
    print(f"bytes: factors={vol['B_factors']/1e6:.1f}MB "
          f"inv={vol['B_inv']/1e6:.1f}MB grads={vol['B_grads']/1e6:.1f}MB "
          f"params={vol['B_params']/1e6:.1f}MB")
    for r in rows:
        if r['cadence'].startswith('production') and r['link'] == 'ici':
            per = {k: v['ms_per_step'] for k, v in r['strategies'].items()}
            print(f"W={r['world']:>3} {r['link']} {r['cadence']}: "
                  f"best={r['best']}  " +
                  ' '.join(f'{k}={v}' for k, v in sorted(per.items())))
    v64 = [r for r in rows if r['world'] == 64 and r['link'] == 'ici'
           and r['cadence'].startswith('production')][0]
    print(json.dumps({'verdict_v64_ici': v64['best'],
                      'strategies': {k: v['ms_per_step'] for k, v in
                                     v64['strategies'].items()}}))


if __name__ == '__main__':
    main()
