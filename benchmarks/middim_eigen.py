"""Mid-dim eigen extension experiment (VERDICT r4 #7).

The 'auto' inverse dispatch sends factor dims > `auto_eigen_max_dim`
(640) to damped Cholesky because the fp32-HIGHEST warm-polish matmuls
blow up at flagship dims (measured 41x at 4609, PERF.md round 3).
Between 640 and ~2304 the *eigen semantics* (joint damping read at
precondition time) are lost to the split operator. This bench measures
whether a CHEAPER polish — HIGH-precision (bf16 3-pass) matmuls and/or
fewer iterations — makes eigen competitive with Cholesky at 1024/2304,
and what it costs in basis accuracy (preconditioning relative error
vs the exact eigh oracle).

Methodology notes (both learned the hard way):
- the warm basis is the exact basis rotated by a *spectral-angle*-
  normalized rotation (`eigh_methods.rand_rotation`, angle 0.1 rad —
  the tracked steady state one firing later); an entry-scaled skew is
  NOT small at these dims (spectral angle grows ~sqrt(dim) and leaves
  polish's capture range — the first cut of this bench did that and
  measured nonsense 0.9 rel errs).
- every timed repeat runs on a distinct input stack: the axon TPU
  tunnel memoizes identical program executions (the round-2
  0.05 ms "eigh" artifact), so same-input min-of-repeats lies.

Per (dim, config): a stack of `n_mats` trained-like SPD factors
(`eigh_methods.trained_like_stack` spectra), one firing =
`eigh_polish` from the warm basis. Cholesky row =
`damped_inverse_stack`. Accuracy metric = `eigh_methods.
precond_rel_err` (the quantity K-FAC consumes).

    python benchmarks/middim_eigen.py [--dims 1024 2304] [--repeats 3]
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from benchmarks import eigh_methods
from benchmarks.eigh_methods import precond_rel_err, trained_like_stack
from distributed_kfac_pytorch_tpu.ops import linalg, pallas_kernels
from distributed_kfac_pytorch_tpu.utils import enable_compilation_cache


def subspace_rotation(rng, n, angle, k=16):
    """Rotation of exact spectral ``angle`` confined to a random
    rank-``k`` subspace: Q = I + U (R_k - I) U^T with U orthonormal
    (QR of an n x k Gaussian) and R_k a k x k rotation of spectral
    angle ``angle`` (`eigh_methods.rand_rotation` at k x k, trivial).

    `eigh_methods.rand_rotation` is exact over the FULL space but costs
    a complex n x n eigh — minutes per matrix at 2304 on this 1-core
    host (the first run of this bench timed out on exactly that); a
    random-subspace rotation keeps the spectral-angle normalization at
    O(n^2 k) and still forces polish to repair mixing across ``k``
    random directions."""
    k = min(k, n)
    u, _ = np.linalg.qr(rng.standard_normal((n, k)))
    rk = eigh_methods.rand_rotation(rng, k, angle)
    return np.eye(n) + u @ (rk - np.eye(k)) @ u.T


def make_variants(dim, n_mats, n_variants, angle=0.1, seed=0):
    """``n_variants`` (stack, warm_basis) pairs with distinct data so
    repeated timings cannot hit the execution-memoization cache; the
    exact (w, v) of variant 0 is kept as the accuracy oracle.

    Only variant 0 gets the exact-eigh treatment (the expensive host
    prep); timing variants i>0 are variant 0 with a distinct diagonal
    jitter — different bytes (cache-busting) but identical shapes and
    fixed iteration counts, so the measured runtime is the same
    program's."""
    rng = np.random.default_rng(seed)
    stack = np.asarray(trained_like_stack(rng, [dim] * n_mats)[dim])
    ws, qs, warm = [], [], []
    for m in stack:
        w, q = np.linalg.eigh(m)
        ws.append(w)
        qs.append(q)
        warm.append(q @ subspace_rotation(rng, dim, angle))
    oracle = (np.stack(ws), np.stack(qs))
    warm0 = jnp.asarray(np.stack(warm), jnp.float32)
    variants = [(jnp.asarray(stack, jnp.float32), warm0)]
    for vi in range(1, n_variants):
        jit = 1e-4 * (1 + vi) * np.eye(dim, dtype=np.float32)
        variants.append((jnp.asarray(stack + jit, jnp.float32), warm0))
    return variants, oracle


def _fetch_scalar(out):
    """Host-fetch one element of the output — a hard data dependency
    that closes the timing window. Per-call ``block_until_ready`` is
    NOT a reliable completion barrier through the tunneled backend
    (bench.py's documented failure mode: calls acknowledged, not
    executed — this bench's first cut recorded a 0.04 ms '2304 eigh'
    exactly that way)."""
    leaf = jax.tree.leaves(out)[0]
    return float(leaf.reshape(-1)[0].real)


def time_variants(fn, variants, repeats):
    """Compile on variant 0, then time one call per distinct variant;
    returns (best seconds, variant-0 output)."""
    out0 = fn(*variants[0])  # compile
    _fetch_scalar(out0)
    best = float('inf')
    for i in range(1, min(repeats + 1, len(variants))):
        args = variants[i]
        t0 = time.perf_counter()
        _fetch_scalar(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best, out0


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--dims', type=int, nargs='+', default=[1024, 2304])
    p.add_argument('--n-mats', type=int, default=4)
    p.add_argument('--repeats', type=int, default=3)
    p.add_argument('--out', default='MIDDIM_EIGEN.json')
    args = p.parse_args(argv)
    if args.repeats < 1:
        p.error('--repeats must be >= 1')
    enable_compilation_cache()

    rows = []
    for dim in args.dims:
        variants, (ws, vs) = make_variants(dim, args.n_mats,
                                           args.repeats + 1)
        configs = [
            ('polish_fp32HIGHEST_8', None, 8),
            ('polish_HIGH_8', jax.lax.Precision.HIGH, 8),
            ('polish_HIGH_4', jax.lax.Precision.HIGH, 4),
        ]
        for label, precision, iters in configs:
            # kfaclint: waive[retrace-jit-in-loop] per-config bench harness: one jit per method config, compile excluded from timing
            fn = jax.jit(jax.vmap(functools.partial(
                linalg.eigh_polish, iters=iters, precision=precision)))
            sec, (qs, ds) = time_variants(fn, variants, args.repeats)
            errs = [precond_rel_err(None, np.asarray(qs[i]),
                                    np.asarray(ds[i]),
                                    exact_wv=(ws[i], vs[i]))
                    for i in range(args.n_mats)]
            rows.append({'dim': dim, 'method': label,
                         'ms_per_firing': round(sec * 1e3, 2),
                         'worst_precond_rel_err':
                             float(f'{np.max(errs):.3g}')})
            print(json.dumps(rows[-1]), flush=True)
        # kfaclint: waive[retrace-jit-in-loop] per-dim bench harness: one jit per dim rung, compile excluded from timing
        fn = jax.jit(lambda s, _q: pallas_kernels.damped_inverse_stack(
            s, 1e-3, 'cholesky'))
        sec, _ = time_variants(fn, variants, args.repeats)
        rows.append({'dim': dim, 'method': 'cholesky',
                     'ms_per_firing': round(sec * 1e3, 2),
                     'worst_precond_rel_err': None})
        print(json.dumps(rows[-1]), flush=True)
        # kfaclint: waive[retrace-jit-in-loop] per-dim bench harness: one jit per dim rung, compile excluded from timing
        fn = jax.jit(lambda s, _q: jnp.linalg.eigh(s))
        sec, _ = time_variants(fn, variants, args.repeats)
        rows.append({'dim': dim, 'method': 'xla_eigh_cold',
                     'ms_per_firing': round(sec * 1e3, 2),
                     'worst_precond_rel_err': 0.0})
        print(json.dumps(rows[-1]), flush=True)

    with open(args.out, 'w') as f:
        json.dump({'n_mats_per_dim': args.n_mats,
                   'backend': jax.default_backend(),
                   'warm_angle_rad': 0.1,
                   'note': 'per-firing decomposition cost of a '
                           f'{args.n_mats}-matrix stack at each dim; '
                           'polish rows = eigh_method auto steady '
                           'state; decide auto_eigen_max_dim',
                   'rows': rows}, f, indent=1)
    print(json.dumps({'rows': rows}))


if __name__ == '__main__':
    main()
