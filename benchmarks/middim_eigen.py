"""Mid-dim eigen extension experiment (VERDICT r4 #7).

The 'auto' inverse dispatch sends factor dims > `auto_eigen_max_dim`
(640) to damped Cholesky because the fp32-HIGHEST warm-polish matmuls
blow up at flagship dims (measured 41x at 4609, PERF.md round 3).
Between 640 and ~2304 the *eigen semantics* (joint damping read at
precondition time) are lost to the split operator. This bench measures
whether a CHEAPER polish — HIGH-precision (bf16 3-pass) matmuls and/or
fewer iterations — makes eigen competitive with Cholesky at 1024/2304,
and what it costs in basis accuracy (preconditioning relative error
vs the exact eigh oracle).

Per (dim, config): a stack of `n_mats` trained-like SPD factors
(log-uniform spectra, like eigh_methods.py), one firing =
`eigh_polish` of a mildly-rotated exact basis (the steady-state of
eigh_method='auto' tracking). Cholesky row = `damped_inverse_stack`.

    python benchmarks/middim_eigen.py [--dims 1024 2304] [--repeats 3]
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from distributed_kfac_pytorch_tpu.ops import linalg, pallas_kernels
from distributed_kfac_pytorch_tpu.utils import enable_compilation_cache


def trained_like_stack(dim, n_mats, seed=0):
    rng = np.random.default_rng(seed)
    mats = []
    for _ in range(n_mats):
        q, _ = np.linalg.qr(rng.normal(size=(dim, dim)))
        d = np.exp(rng.uniform(np.log(1e-4), np.log(10.0), dim))
        mats.append((q * d) @ q.T)
    return jnp.asarray(np.stack(mats), jnp.float32)


def perturbed_basis(stack, angle=3e-2, seed=1):
    """(exact (w, v) per matrix, slightly-rotated bases) — the exact
    decomposition is computed ONCE per stack and reused as the
    precond_err oracle (cold eigh at these dims is exactly the
    expensive thing under study)."""
    ws, qs = jnp.linalg.eigh(stack)
    rng = np.random.default_rng(seed)
    out = []
    for i in range(stack.shape[0]):
        s = rng.normal(size=stack.shape[1:])
        skew = jnp.asarray((s - s.T) / 2 * angle, jnp.float32)
        g, _ = jnp.linalg.qr(jnp.eye(stack.shape[1]) + skew)
        out.append(qs[i] @ g)
    return (ws, qs), jnp.stack(out)


def precond_err(exact_wv, q, d, damping=1e-3):
    """Relative error of (A+λ)^-1 applied via (Q, d) vs the exact
    eigh oracle (w, v)."""
    w, v = exact_wv
    x = jnp.eye(v.shape[-1], dtype=jnp.float32)[:, :8]
    exact = v @ ((v.T @ x) / (w + damping)[:, None])
    approx = q @ ((q.T @ x) / (d + damping)[:, None])
    return float(jnp.linalg.norm(approx - exact)
                 / jnp.linalg.norm(exact))


def time_fn(fn, *args, repeats=3):
    out = jax.block_until_ready(fn(*args))  # compile
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    return min(times), out


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--dims', type=int, nargs='+', default=[1024, 2304])
    p.add_argument('--n-mats', type=int, default=4)
    p.add_argument('--repeats', type=int, default=3)
    p.add_argument('--out', default='MIDDIM_EIGEN.json')
    args = p.parse_args(argv)
    enable_compilation_cache()

    rows = []
    for dim in args.dims:
        stack = trained_like_stack(dim, args.n_mats)
        (ws, vs), q_prev = perturbed_basis(stack)
        configs = [
            ('polish_fp32HIGHEST_8', None, 8),
            ('polish_HIGH_8', jax.lax.Precision.HIGH, 8),
            ('polish_HIGH_4', jax.lax.Precision.HIGH, 4),
        ]
        for label, precision, iters in configs:
            fn = jax.jit(jax.vmap(functools.partial(
                linalg.eigh_polish, iters=iters, precision=precision)))
            sec, (qs, ds) = time_fn(fn, stack, q_prev,
                                    repeats=args.repeats)
            errs = [precond_err((ws[i], vs[i]), qs[i], ds[i])
                    for i in range(args.n_mats)]
            rows.append({'dim': dim, 'method': label,
                         'ms_per_firing': round(sec * 1e3, 2),
                         'worst_precond_rel_err':
                             float(np.max(errs))})
            print(json.dumps(rows[-1]), flush=True)
        fn = jax.jit(lambda s: pallas_kernels.damped_inverse_stack(
            s, 1e-3, 'cholesky'))
        sec, _ = time_fn(fn, stack, repeats=args.repeats)
        rows.append({'dim': dim, 'method': 'cholesky',
                     'ms_per_firing': round(sec * 1e3, 2),
                     'worst_precond_rel_err': None})
        print(json.dumps(rows[-1]), flush=True)
        fn = jax.jit(jax.vmap(jnp.linalg.eigh))
        sec, _ = time_fn(fn, stack, repeats=args.repeats)
        rows.append({'dim': dim, 'method': 'xla_eigh_cold',
                     'ms_per_firing': round(sec * 1e3, 2),
                     'worst_precond_rel_err': 0.0})
        print(json.dumps(rows[-1]), flush=True)

    with open(args.out, 'w') as f:
        json.dump({'n_mats_per_dim': args.n_mats,
                   'backend': jax.default_backend(),
                   'note': 'per-firing decomposition cost of a '
                           f'{args.n_mats}-matrix stack at each dim; '
                           'polish rows = eigh_method auto steady '
                           'state; decide auto_eigen_max_dim',
                   'rows': rows}, f, indent=1)
    print(json.dumps({'rows': rows}))


if __name__ == '__main__':
    main()
