"""Ring-attention perf characterization (the round-4 verdict's last
uncharacterized subsystem: "correctness tests + dryrun only").

The reference has no long-context machinery at all (sequence models are
BPTT-35 truncated — reference examples/torch_language_model.py:52,
SURVEY.md §5), so there is no reference number here; the bench
characterizes this framework's own ring attention
(``parallel/sequence.py``) on the axes that decide whether it is usable
at scale:

1. **On-chip per-device compute** (real TPU): one ring device's exact
   compute schedule — s online-softmax folds over (T_local x T_local)
   blocks, the same fold code ``ring_self_attention`` runs between
   ``ppermute``s — vs monolithic ``local_causal_attention`` at the same
   global sequence. A real s-device ring costs ~full/s per device plus
   fold overhead; this leg measures that overhead directly on the MXU.
   (Collectives cannot run single-chip; the fold loop is the entire
   per-device compute, so emulating it IS the honest on-chip number.
   ``tests/test_sequence_parallel.py`` pins the emulation's outputs to
   monolithic attention rows so the bench measures the real algorithm.)

2. **Memory ceiling** (real TPU): peak HBM for monolithic attention's
   O(S^2) logits vs the ring's O(T_local^2) block, including the OOM
   probe at the first monolithic-infeasible S. Each leg is its own
   subprocess (flagship methodology: a dropped oversized compile
   poisons the tunneled device session).

3. **ICI overlap model** (analytic, parameterized like
   kaisa_decision_model.py — one real chip, no ICI to measure): per
   ring step a device sends its K/V block (2*B*T_local*H*D*bytes) while
   folding one block; comm hides iff block_bytes/ici_bw < measured
   block compute time. Reports the break-even T_local.

4. **CPU-mesh scaling shape** (8 virtual devices, 1-core host —
   RELATIVE ORDERING ONLY): ring at s in {2,4,8} vs monolithic at the
   same global S. All s devices share one core, so ideal ring wall time
   equals monolithic (same total FLOPs); the measured ratio is the
   fold + ppermute overhead under equal compute.

Timing follows bench.py's documented methodology: chained calls (the
attention output perturbs the next query, defeating the tunnel's
execution memoization) timed as one window closed by a scalar host
fetch, with a 100%-MFU FLOPs floor rejecting elided executions.

    python benchmarks/ring_attention_bench.py [--batch 4] [--heads 16]
        [--head-dim 64] [--ici-gbps 40] [--out RING_ATTENTION.json]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def attn_fwd_flops(batch, seq_q, seq_k, heads, head_dim):
    """QK^T + AV matmul FLOPs (causal mask zeroes but does not skip)."""
    return 4 * batch * heads * seq_q * seq_k * head_dim


def ring_device_schedule(q, k_stack, v_stack, *, device_idx, ring_size,
                         causal=True):
    """One ring device's exact compute: fold ``ring_size`` K/V blocks
    with the online-softmax update, no collectives.

    Mirrors ``ring_self_attention``'s ``fold_block`` — same
    ``_block_attend`` + shared ``_fold_update`` accumulation
    (parallel/sequence.py), so the measured schedule cannot drift from
    the shipped algorithm — with ``ppermute`` replaced by indexing into
    the pre-staged block stacks: after ``step`` rotations device
    ``idx`` holds the block of device ``(idx - step) % s``.

    q: (B, T_local, H, D); k_stack/v_stack: (s, B, T_local, H, D).
    Returns (B, T_local, H, D) fp32, equal to the corresponding row
    block of monolithic attention (pinned in test_sequence_parallel).
    """
    import jax
    import jax.numpy as jnp

    from distributed_kfac_pytorch_tpu.parallel import sequence as seq

    s = ring_size
    b, t, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    local_pos = jnp.arange(t)
    qpos = device_idx * t + local_pos

    def body(step, carry):
        o, m, l = carry
        src = (device_idx - step) % s
        kpos = src * t + local_pos
        k_cur = jax.lax.dynamic_index_in_dim(k_stack, src, 0,
                                             keepdims=False)
        v_cur = jax.lax.dynamic_index_in_dim(v_stack, src, 0,
                                             keepdims=False)
        bm, bo, bl = seq._block_attend(q, k_cur, v_cur,
                                       scale, qpos, kpos, causal)
        return seq._fold_update(o, m, l, bm, bo, bl)

    o0 = jnp.zeros((b, t, h, d), jnp.float32)
    m0 = jnp.full((b, h, t), seq._NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, s, body, (o0, m0, l0))
    l = jnp.maximum(jnp.moveaxis(l, 1, 2)[..., None], 1e-30)
    return o / l


# ---------------------------------------------------------------------------
# On-chip phases (fresh subprocess each, flagship methodology)
# ---------------------------------------------------------------------------

def emit(obj):
    print(json.dumps(obj), flush=True)


def _peak_hbm_bytes():
    """Device peak-allocation high-water mark, or None where the
    backend doesn't expose memory_stats (e.g. some tunneled sessions)."""
    import jax
    try:
        stats = jax.local_devices()[0].memory_stats()
        return int(stats.get('peak_bytes_in_use')) if stats else None
    except Exception:
        return None


def _time_attn(fn, q, k, v, flops, repeats=8, attempts=3):
    """Chained-window timing: each call's output perturbs the next
    query (hard data dependency — the tunnel cannot memoize or elide),
    one window per batch closed by a scalar host fetch, readings below
    the 100%-MFU floor discarded (bench.py methodology)."""
    import jax
    import jax.numpy as jnp

    import bench as B

    _, floor_peak = B.detected_tpu_peak()
    floor_ms = flops / floor_peak * 1e3

    @jax.jit
    def step(q, k, v):
        out = fn(q, k, v)
        # Perturbation must clear the operand dtype's ULP (bf16 ULP at
        # |q|~0.1 is ~4e-4) or q_next rounds back to q bitwise and the
        # anti-memoization chain goes inert; 1e-3*out flips a large
        # fraction of elements while drifting |q| by <1% over a full
        # timing run.
        q_next = q + (1e-3 * out).astype(q.dtype)
        return q_next, out[0, 0, 0, 0]

    q, probe = step(q, k, v)  # compile + warm
    float(probe)
    readings = []
    for _ in range(attempts):
        t0 = time.perf_counter()
        for _ in range(repeats):
            q, probe = step(q, k, v)
        float(probe)  # closes the window
        per_call = (time.perf_counter() - t0) / repeats * 1000.0
        if per_call >= floor_ms:
            readings.append(per_call)
    if not readings:
        raise RuntimeError(
            f'every reading fell below the {floor_ms:.3f} ms FLOPs '
            'floor — cached/elided execution suspected')
    return sorted(readings)[len(readings) // 2]


def phase_full(args):
    import jax.numpy as jnp
    import numpy as np

    from distributed_kfac_pytorch_tpu.parallel import sequence as seq

    b, h, d, s_len = args.batch, args.heads, args.head_dim, args.seq
    dt = jnp.float32 if args.fp32_operands else jnp.bfloat16
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(b, s_len, h, d) * 0.1, dt)  # noqa: E731
    q, k, v = mk(), mk(), mk()
    flops = attn_fwd_flops(b, s_len, s_len, h, d)
    ms = _time_attn(seq.local_causal_attention, q, k, v, flops)
    emit({'phase_result': round(ms, 3),
          'tflops': round(flops / (ms * 1e-3) / 1e12, 2),
          'peak_hbm_bytes': _peak_hbm_bytes(),
          'logits_bytes': b * h * s_len * s_len * 4})


def phase_ringdev(args):
    import jax.numpy as jnp
    import numpy as np

    b, h, d = args.batch, args.heads, args.head_dim
    s = args.ring
    t_local = args.seq // s
    dt = jnp.float32 if args.fp32_operands else jnp.bfloat16
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, t_local, h, d) * 0.1, dt)
    kst = jnp.asarray(rng.randn(s, b, t_local, h, d) * 0.1, dt)
    vst = jnp.asarray(rng.randn(s, b, t_local, h, d) * 0.1, dt)
    # Device s-1 (every block causally live) — compute cost is
    # idx-independent since masked blocks are computed, not skipped.
    fn = lambda q, k, v: ring_device_schedule(  # noqa: E731
        q, k, v, device_idx=s - 1, ring_size=s)
    flops = s * attn_fwd_flops(b, t_local, t_local, h, d)
    ms = _time_attn(fn, q, kst, vst, flops)
    emit({'phase_result': round(ms, 3),
          'tflops': round(flops / (ms * 1e-3) / 1e12, 2),
          'peak_hbm_bytes': _peak_hbm_bytes(),
          'block_bytes': b * h * t_local * t_local * 4,
          'kv_wire_bytes_per_step': (2 * b * t_local * h * d
                                     * jnp.dtype(dt).itemsize)})


def _time_attn_grad(fn, q, k, v, flops, repeats=4, attempts=3):
    """Chained-window timing of value_and_grad (the training path):
    the q-gradient perturbs the next query.

    Differentiates wrt ALL of (q, k, v) — a q-only grad lets XLA
    dead-code-eliminate the dK = dS^T q and dV = P^T dO matmuls (an
    earlier cut measured exactly 2.04x fwd, the 2-matmul backward,
    while reporting the 3x-fwd convention's TFLOP/s)."""
    import jax

    import bench as B

    _, floor_peak = B.detected_tpu_peak()
    floor_ms = flops / floor_peak * 1e3

    @jax.jit
    def step(q, k, v):
        val, (gq, gk, gv) = jax.value_and_grad(
            lambda q, k, v: fn(q, k, v).sum(), argnums=(0, 1, 2))(q, k, v)
        # Full-tensor reductions of gk/gv keep every backward matmul
        # live (a single-element probe could be slice-simplified away);
        # q carries the anti-memoization chain.
        q_next = q + (1e-3 * gq).astype(q.dtype)
        return q_next, val + gk.mean() + gv.mean()

    q, probe = step(q, k, v)
    float(probe)
    readings = []
    for _ in range(attempts):
        t0 = time.perf_counter()
        for _ in range(repeats):
            q, probe = step(q, k, v)
        float(probe)
        per_call = (time.perf_counter() - t0) / repeats * 1000.0
        if per_call >= floor_ms:
            readings.append(per_call)
    if not readings:
        raise RuntimeError('all readings below FLOPs floor')
    return sorted(readings)[len(readings) // 2]


def phase_chunked(args):
    """Chunked (memory-efficient) single-device attention: fwd or
    fwd+bwd (--grad) at global seq with --ring reused as seq/block."""
    import jax.numpy as jnp
    import numpy as np

    from distributed_kfac_pytorch_tpu.parallel import sequence as seq

    b, h, d, s_len = args.batch, args.heads, args.head_dim, args.seq
    block = s_len // args.ring
    dt = jnp.float32 if args.fp32_operands else jnp.bfloat16
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(b, s_len, h, d) * 0.1, dt)  # noqa: E731
    q, k, v = mk(), mk(), mk()
    fn = lambda q, k, v: seq.chunked_causal_attention(  # noqa: E731
        q, k, v, block_size=block)
    fwd = attn_fwd_flops(b, s_len, s_len, h, d)
    if args.grad:
        ms = _time_attn_grad(fn, q, k, v, 3 * fwd)
        flops = 3 * fwd
    else:
        ms = _time_attn(fn, q, k, v, fwd)
        flops = fwd
    emit({'phase_result': round(ms, 3),
          'tflops': round(flops / (ms * 1e-3) / 1e12, 2),
          'block_size': block,
          'live_logits_gb': round(b * h * s_len * block * 4 / 2**30, 2)})


def phase_full_grad(args):
    """Monolithic attention fwd+bwd — probes the training-memory wall."""
    import jax.numpy as jnp
    import numpy as np

    from distributed_kfac_pytorch_tpu.parallel import sequence as seq

    b, h, d, s_len = args.batch, args.heads, args.head_dim, args.seq
    dt = jnp.float32 if args.fp32_operands else jnp.bfloat16
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(b, s_len, h, d) * 0.1, dt)  # noqa: E731
    q, k, v = mk(), mk(), mk()
    ms = _time_attn_grad(seq.local_causal_attention, q, k, v,
                         3 * attn_fwd_flops(b, s_len, s_len, h, d))
    emit({'phase_result': round(ms, 3)})


def phase_cpumesh(args):
    """Scaling shape on the 8-virtual-device CPU mesh — relative
    ordering only on a shared-core host.

    Platform override must be programmatic: the axon sitecustomize sets
    ``jax_platforms`` in every interpreter, so ``JAX_PLATFORMS`` /
    ``XLA_FLAGS`` env vars are silently ignored in this image (the
    conftest/dryrun mechanism). The compilation cache stays off — warm
    cache reads segfault on the multi-device CPU backend."""
    import jax

    jax.config.update('jax_platforms', 'cpu')
    from distributed_kfac_pytorch_tpu import compat
    compat.set_cpu_device_count(8)

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from distributed_kfac_pytorch_tpu.parallel import sequence as seq
    from distributed_kfac_pytorch_tpu.utils import (
        disable_compilation_cache,
        raise_cpu_collective_timeouts,
    )

    raise_cpu_collective_timeouts()
    disable_compilation_cache()
    assert jax.default_backend() == 'cpu' and jax.device_count() == 8

    b, h, d, s_len = 2, 4, 32, args.seq
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.randn(b, s_len, h, d) * 0.1, jnp.float32)
    q, k, v = mk(), mk(), mk()

    def timed(fn, *xs):
        out = fn(*xs)
        float(out[0, 0, 0, 0].astype(jnp.float32))
        best = float('inf')
        for _ in range(3):
            t0 = time.perf_counter()
            out = fn(*xs)
            float(out[0, 0, 0, 0].astype(jnp.float32))
            best = min(best, time.perf_counter() - t0)
        return best * 1000.0

    rows = {'full_1dev': round(
        timed(jax.jit(seq.local_causal_attention), q, k, v), 2)}
    for s in (2, 4, 8):
        mesh = Mesh(np.asarray(jax.devices()[:s]), (seq.SEQ_AXIS,))
        # kfaclint: waive[retrace-jit-in-loop] per-mesh-size bench harness: one program per shard count, compile excluded from timing
        ring = jax.jit(jax.shard_map(
            seq.ring_self_attention, mesh=mesh,
            in_specs=(P(None, seq.SEQ_AXIS),) * 3,
            out_specs=P(None, seq.SEQ_AXIS), check_vma=False))
        rows[f'ring_{s}dev'] = round(timed(ring, q, k, v), 2)
    emit({'phase_result': rows})


# ---------------------------------------------------------------------------
# Orchestrator
# ---------------------------------------------------------------------------

def spawn(phase, seq=0, ring=0, args=None, env=None, timeout=1200,
          grad=False):
    cmd = [sys.executable, os.path.abspath(__file__), '--phase', phase,
           '--seq', str(seq), '--ring', str(ring),
           '--batch', str(args.batch), '--heads', str(args.heads),
           '--head-dim', str(args.head_dim)]
    if args.fp32_operands:
        cmd.append('--fp32-operands')
    if grad:
        cmd.append('--grad')
    run_env = dict(os.environ)
    if env:
        run_env.update(env)
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout, cwd=REPO, env=run_env)
    except subprocess.TimeoutExpired:
        return None, {'error': 'timeout'}
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            obj = json.loads(line)
            return obj['phase_result'], obj
        except Exception:
            continue
    from bench import extract_failure_line
    msg = extract_failure_line(out.stderr)
    return None, {'error': msg or f'rc={out.returncode}'}


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--batch', type=int, default=4)
    p.add_argument('--heads', type=int, default=16)
    p.add_argument('--head-dim', type=int, default=64)
    p.add_argument('--ici-gbps', type=float, default=40.0,
                   help='effective per-link ICI bandwidth (PARAMETER, '
                        'not a measurement — one chip here); 40 GB/s is '
                        'a conservative public v4-class figure')
    p.add_argument('--seq', type=int, default=0)
    p.add_argument('--ring', type=int, default=0)
    p.add_argument('--phase', default=None)
    p.add_argument('--cpu-seq', type=int, default=1024)
    p.add_argument('--skip-onchip', action='store_true',
                   help='keep the on-chip rows already in --out and '
                        'rerun only the CPU-mesh leg')
    p.add_argument('--grad', action='store_true',
                   help='time value_and_grad instead of forward '
                        '(chunked / full_grad phases)')
    p.add_argument('--chunked-only', action='store_true',
                   help='keep existing rows in --out and (re)run only '
                        'the chunked/memory-efficient legs')
    p.add_argument('--fp32-operands', action='store_true',
                   help='A/B control: upcast q/k/v to fp32 before the '
                        'attention op (the pre-optimization behavior; '
                        'the product contract is operand-dtype matmuls '
                        'with fp32 accumulation)')
    p.add_argument('--out', default=os.path.join(REPO,
                                                 'RING_ATTENTION.json'))
    args = p.parse_args(argv)

    if args.phase:
        if args.phase != 'cpumesh':
            # On-chip workers see the tunneled TPU exactly as bench.py
            # does (incl. the persistent compile cache); the cpumesh
            # worker configures its own platform and must NOT enable
            # the cache (multi-device-CPU segfault gotcha).
            import bench  # noqa: F401
        {'full': phase_full, 'ringdev': phase_ringdev,
         'chunked': phase_chunked, 'full_grad': phase_full_grad,
         'cpumesh': phase_cpumesh}[args.phase](args)
        return

    if args.skip_onchip or args.chunked_only:
        # Partial reruns PATCH an existing artifact; refuse to silently
        # fall back to the full (expensive, OOM-probing) sweep.
        if not os.path.exists(args.out):
            raise SystemExit(f'{args.out} not found: --skip-onchip/'
                             '--chunked-only patch an existing artifact')
        with open(args.out) as f:
            result = json.load(f)
    else:
        result = _run_onchip_legs(args)
        result['fp32_operand_controls'] = _run_fp32_controls(args)

    # --skip-onchip refreshes ONLY the CPU-mesh leg (its help text);
    # chunked on-chip legs run on a full sweep or --chunked-only.
    if args.chunked_only or (not args.skip_onchip
                             and result.get('chunked') is None):
        result['chunked'] = _run_chunked_legs(args)
        with open(args.out, 'w') as f:
            json.dump(result, f, indent=1)
        if args.chunked_only:
            print(json.dumps({'wrote': args.out}))
            return

    # Leg 4: CPU-mesh scaling shape (the worker sets its own platform —
    # env overrides are dead under the axon sitecustomize).
    _, extra = spawn('cpumesh', seq=args.cpu_seq, args=args,
                     timeout=3600)
    result['cpumesh'] = {
        'note': 'RELATIVE ORDERING ONLY: 8 virtual devices on a '
                'shared-core host; equal total FLOPs at every s, so '
                'ratio to full_1dev is pure fold+ppermute overhead',
        'seq': args.cpu_seq,
        'ms': extra.get('phase_result', extra.get('error'))}

    with open(args.out, 'w') as f:
        json.dump(result, f, indent=1)
    print(json.dumps({'wrote': args.out}))


def _run_fp32_controls(args):
    """A/B control rows: operands upcast to fp32 before the attention
    op (the pre-optimization compute behavior; ring wire traffic was
    always input-dtype). Part of the standard sweep so the artifact is
    reproducible from one invocation."""
    import copy

    ctl_args = copy.copy(args)
    ctl_args.fp32_operands = True
    out = {'note': 'operands upcast to fp32 before the attention op '
                   '(pre-optimization compute behavior). '
                   'kv_wire_bytes_per_step reflects the control\'s own '
                   'fp32 inputs; the product ring always permutes '
                   'input-dtype blocks.'}
    for name, phase, s_len, ring in (
            ('full_seq4096', 'full', 4096, 0),
            ('ringdev_seq4096_r8', 'ringdev', 4096, 8),
            ('ringdev_seq16384_r8', 'ringdev', 16384, 8)):
        ms, extra = spawn(phase, seq=s_len, ring=ring, args=ctl_args)
        out[name] = extra if ms else {'error': extra.get('error')}
        print(json.dumps({name: out[name]}), flush=True)
    return out


def _run_chunked_legs(args):
    """Single-device memory-efficient attention: fwd + the TRAINING
    path (fwd+bwd through the checkpointed scan), against monolithic
    attention's gradient wall."""
    out = {'note': 'chunked_causal_attention (block fold + per-block '
                   'jax.checkpoint). grad tflops use the 3x-fwd model-'
                   'FLOPs convention (checkpoint recompute not counted, '
                   'so achieved hardware TFLOP/s is ~4/3 of reported)',
           'rows': []}
    for phase, s_len, ring, grad in (
            ('full_grad', 2048, 1, True),
            # 4096 is monolithic training's largest FITTING size; the
            # wall is 8192, where even the forward OOMs (onchip rows),
            # so no full_grad probe is needed there.
            ('full_grad', 4096, 1, True),
            ('chunked', 4096, 4, True),
            ('chunked', 8192, 8, True),
            ('chunked', 16384, 16, True),
            ('chunked', 16384, 16, False)):
        ms, extra = spawn(phase, seq=s_len, ring=ring, args=args,
                          grad=grad, timeout=2400)
        row = {'phase': phase, 'seq': s_len, 'grad': grad,
               'ms': ms if ms else extra.get('error')}
        if ms:
            for key in ('tflops', 'block_size', 'live_logits_gb'):
                if extra.get(key) is not None:
                    row[key] = extra[key]
        out['rows'].append(row)
        print(json.dumps(row), flush=True)
    return out


def _run_onchip_legs(args):
    dt = 'fp32' if args.fp32_operands else 'bf16'
    result = {'shape': {'batch': args.batch, 'heads': args.heads,
                        'head_dim': args.head_dim,
                        'dtype': f'{dt} operands, fp32 accumulate/'
                                 'softmax (the module contract)'},
              'flops_note': 'fwd-only characterization of the attention '
                            'op; training cost ~3x per matmul-backward '
                            'convention',
              'onchip': [], 'cpumesh': None}

    # Leg 1+2: monolithic vs per-ring-device compute + memory.
    for s_len, ring in ((2048, 8), (4096, 8), (8192, 8), (16384, 8),
                        (32768, 16)):
        row = {'seq': s_len, 'ring': ring}
        if s_len <= 8192:   # 8192: expected OOM probe (17 GB logits)
            ms, extra = spawn('full', seq=s_len, args=args)
            row['full_ms'] = ms if ms else extra.get('error')
            if ms:
                row['full_tflops'] = extra.get('tflops')
                row['full_peak_hbm_gb'] = (
                    round(extra['peak_hbm_bytes'] / 2**30, 2)
                    if extra.get('peak_hbm_bytes') else None)
            row['full_logits_gb'] = round(
                args.batch * args.heads * s_len * s_len * 4 / 2**30, 2)
        ms, extra = spawn('ringdev', seq=s_len, ring=ring, args=args)
        row['ringdev_ms'] = ms if ms else extra.get('error')
        if ms:
            row['ringdev_tflops'] = extra.get('tflops')
            row['ringdev_peak_hbm_gb'] = (
                round(extra['peak_hbm_bytes'] / 2**30, 2)
                if extra.get('peak_hbm_bytes') else None)
            row['block_ms'] = round(ms / ring, 3)
            wire = extra['kv_wire_bytes_per_step']
            row['kv_wire_mb_per_step'] = round(wire / 2**20, 2)
            comm_ms = wire / (args.ici_gbps * 1e9) * 1e3
            row['ici_comm_ms_per_step_at_param_bw'] = round(comm_ms, 3)
            row['comm_hidden'] = bool(comm_ms < ms / ring)
            if isinstance(row.get('full_ms'), float):
                ideal = row['full_ms'] / ring
                row['fold_overhead_vs_ideal'] = round(ms / ideal - 1, 3)
        result['onchip'].append(row)
        print(json.dumps(row), flush=True)

    # ICI overlap verdict from MEASURED rows only (an earlier pure-
    # quadratic extrapolation from the largest block predicted a ~306-
    # token comm-bound crossover that the measured small-block rows
    # refute: small folds are overhead-dominated, i.e. even SLOWER than
    # quadratic, so comm hides even more easily there).
    margins = {}
    for r in result['onchip']:
        if isinstance(r.get('ringdev_ms'), float):
            t_local = r['seq'] // r['ring']
            comm = r['ici_comm_ms_per_step_at_param_bw']
            # Key by (seq, ring): distinct rows can share one T_local.
            margins[f's{r["seq"]}_r{r["ring"]}_tl{t_local}'] = round(
                r['block_ms'] / comm, 1)
    if margins:
        result['ici_overlap_margin'] = margins
        result['ici_overlap_note'] = (
            'block-fold compute time / per-step K/V transfer time at '
            f'the {args.ici_gbps} GB/s ICI parameter; >1 means comm '
            'fully overlaps. Every measured block size overlaps '
            f'(min margin {min(margins.values())}x).')
    return result


if __name__ == '__main__':
    main()
