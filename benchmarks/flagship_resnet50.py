"""Flagship on-chip numbers: ResNet-50 (config 2) and ResNet-152-class
decompositions (config 5) — the BASELINE.md rows that previously had no
recorded on-chip measurement (round-2 verdict, Missing #1).

The tunneled dev chip drops oversized programs (PERF.md "Known infra
limits"): one monolithic ResNet-50 K-FAC train step exceeds the
remote-compile size limit. Cadence is already *static program
structure* in this framework, so the step decomposes into separately
compiled scanned programs per phase — each measured on the real chip,
composed into per-cadence totals:

  sgd        fwd+bwd+momentum                        (batch B, 176px)
  precond    + capture + precondition + KL clip      (every-iter work)
  factors    + factor EWMA                           (factor-step work)
  firing     warm inverse firing over the REAL factor set, timed as its
             own compiled program (decomposition cost is batch- and
             spatial-independent: it sees only the (d, d) factors)

  total(f, i) = precond + (factors - precond)/f + firing/i

Reference cadences composed: stress (1, 10), ImageNet default (10, 100
— torch_imagenet_resnet.py:75-78), production (50, 500 —
launch_node_torch_imagenet.sh:73-87).

Config 5: ResNet-152's full factor set (bf16 factors + fp32
decompositions, BASELINE.md config 5) through the same real bucketed
decomposition path.

EVERY leg runs in its own subprocess: a dropped oversized compile
poisons the device session (observed: every call after the failed
monolithic capture+factors+inverse compile returns 'UNAVAILABLE: TPU
device error'), so isolation is correctness, not hygiene. Legs that
fail are reported as failed — never silently substituted (the round-2
verdict critique of bench_matrix's resnet18 fallback).

    python benchmarks/flagship_resnet50.py [--iters 20] [--batch 32]
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def emit(obj):
    print(json.dumps(obj), flush=True)


# ---------------------------------------------------------------------------
# Single-phase workers (run in a fresh process via --phase)
# ---------------------------------------------------------------------------

def _setup(model_name, batch, image, model_dtype=None, remat=False,
           **kfac_kw):
    import jax
    import jax.numpy as jnp
    import optax

    # Importing bench also enables the persistent compilation cache
    # for this worker process.
    import bench as B
    from distributed_kfac_pytorch_tpu import KFAC
    from distributed_kfac_pytorch_tpu.models import imagenet_resnet

    # bf16 model compute = the TPU-native analogue of the reference's
    # fp16 production ImageNet recipe (launch_node_torch_imagenet.sh:
    # 73-87 passes --fp16); also what makes batch 128 @ 224px fit in a
    # single v5e's 16 GB HBM (fp32 activations RESOURCE_EXHAUST there).
    dt = {None: jnp.float32, 'fp32': jnp.float32,
          'bf16': jnp.bfloat16}[model_dtype]
    model = imagenet_resnet.get_model(model_name, dtype=dt, remat=remat)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, image, image, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, 1000)
    kfac = KFAC(model, factor_update_freq=1, inv_update_freq=1,
                damping=0.003, lr=0.1, **kfac_kw)
    variables, kstate = kfac.init(jax.random.PRNGKey(0), x)
    return (jax, jnp, optax, B, model, kfac, variables, kstate, x, y)


def phase_step_leg(model_name, batch, image, mode, n_iters,
                   model_dtype=None, remat=False, **kfac_kw):
    """sgd | capture | precond | factors | inv: scanned train-step
    variants ('capture' = interception-only, no K-FAC math)."""
    (jax, jnp, optax, B, model, kfac, variables, kstate, x, y) = _setup(
        model_name, batch, image, model_dtype=model_dtype, remat=remat,
        **kfac_kw)
    params = variables['params']
    extra = {k: v for k, v in variables.items() if k != 'params'}
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    def loss(out):
        return B.loss_fn(out, y)

    if mode == 'sgd':
        def body(carry, _):
            params, opt_state, extra = carry

            def wrapped(p):
                out, updated = model.apply({'params': p, **extra}, x,
                                           mutable=['batch_stats'])
                return loss(out), updated
            (l, updated), grads = jax.value_and_grad(
                wrapped, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, {**extra, **updated}), l
        carry0 = (params, opt_state, extra)
    elif mode == 'capture':
        # Interception-only leg: fwd/bwd through KFACCapture (sows +
        # probes) with the SGD update — isolates the capture machinery
        # from the K-FAC math (the every-iter breakdown's middle term).
        def body(carry, _):
            params, opt_state, extra = carry
            l, _, grads, captures, updated = kfac.capture.loss_and_grads(
                loss, params, x, extra_vars=extra,
                mutable_cols=('batch_stats',))
            # Consume every capture — every call of every layer — so
            # none is dead-code-eliminated (weight-shared models have
            # multiple calls per layer).
            probe = sum(t.reshape(-1)[0].astype(jnp.float32)
                        for c in captures.values()
                        for which in ('a', 'g')
                        for t in c[which])
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, {**extra, **updated}), l + probe * 0
        carry0 = (params, opt_state, extra)
    else:
        # 'nofactor' = the true static-cadence non-factor-update step:
        # plain autodiff (intercept=False — no sows/probes; the capture
        # cost is NOT DCE'd by XLA when captures go unused) +
        # precondition + KL clip. This is what (1 - 1/f) of production
        # steps cost; 'precond' keeps the old capturing variant for the
        # capture-cost decomposition.
        flags = {'precond': (False, False),
                 'nofactor': (False, False),
                 'factors': (True, False),
                 'inv': (True, True)}[mode]

        def body(carry, _):
            params, opt_state, kst, extra = carry
            l, _, grads, captures, updated = kfac.capture.loss_and_grads(
                loss, params, x, extra_vars=extra,
                mutable_cols=('batch_stats',),
                intercept=mode != 'nofactor')
            g, kst = kfac.step(kst, grads, captures,
                               factor_update=flags[0],
                               inv_update=flags[1])
            updates, opt_state = tx.update(g, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, kst, {**extra, **updated}), l
        carry0 = (params, opt_state, kstate, extra)

    # Donated carry: time_chained chains carry = run(carry), so the
    # previous carry is dead at each call — donation halves the
    # resident (params, opt_state, kstate) footprint, the difference
    # between fitting and OOMing the monolithic b128 remat legs (the
    # LM flagship's memory lesson, benchmarks/flagship_lm.py:240).
    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(carry):
        carry, losses = jax.lax.scan(body, carry, None, length=n_iters)
        return carry, losses[-1]

    floor = B.flops_floor_ms(kfac, variables, x, y,
                             mutable_cols=('batch_stats',))
    ms = B.time_chained(run, carry0, n_iters, floor_ms=floor, leg=mode)
    # Hand-counted model-math MFU (fwd+bwd FLOPs over wall time; K-FAC
    # work is overhead, so its legs read lower — VERDICT r3 ask #2).
    peak, _ = B.detected_tpu_peak()
    mfu = None
    if peak:
        flops = B.model_flops_per_step(kfac, params, x, y, extra)
        mfu = round(flops / (ms * 1e-3) / peak, 4)
    return ms, mfu


def phase_accum_leg(model_name, batch, image, mode, n_iters, accum=2,
                    model_dtype=None, remat=False, **kfac_kw):
    """b{batch*accum}-equivalent step via gradient accumulation:
    ``accum`` micro-batches of ``batch`` per optimizer step — the
    per-chip operating point at the saturating global batch (bf16
    K-FAC at b128 @224px OOMs monolithically; b128 = 2 x b64 micro
    steps, the library's ``build_train_step(grad_accum_steps=2)``
    semantics: averaged grads, averaged factor contributions with the
    micro-mean G rescale, capture only on factor steps).

    modes: 'accum_nofactor' (plain micro autodiff + precond + clip) |
    'accum_factors' (capture + factor EWMA on this step).
    """
    (jax, jnp, optax, B, model, kfac, variables, kstate, x, y) = _setup(
        model_name, batch, image, model_dtype=model_dtype, remat=remat,
        **kfac_kw)
    from distributed_kfac_pytorch_tpu.layers import base as L
    params = variables['params']
    extra = {k: v for k, v in variables.items() if k != 'params'}
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)
    do_factors = mode == 'accum_factors'
    xs = jnp.stack([x] * accum)
    ys = jnp.stack([y] * accum)

    def loss(out, yy):
        return B.loss_fn(out, yy)

    def contribs_of(captures):
        from distributed_kfac_pytorch_tpu.capture import subsample_captures
        cdt = kfac.factor_compute_dtype
        # Mirror the library factor paths (update_factors /
        # local_factor_contribs): thinning applies before contraction.
        captures = subsample_captures(captures, kfac.factor_batch_fraction)
        return {name: {'A': L.compute_a_factor(s, captures[name]['a'],
                                               compute_dtype=cdt),
                       'G': L.compute_g_factor(s, captures[name]['g'],
                                               compute_dtype=cdt)}
                for name, s in kfac.specs.items()}

    def body(carry, _):
        params, opt_state, kst, extra = carry

        def micro(mcarry, mb):
            extra_c, gsum, csum = mcarry
            mx, my = mb
            l, _, grads, captures, updated = kfac.capture.loss_and_grads(
                lambda out: loss(out, my), params, mx, extra_vars=extra_c,
                mutable_cols=('batch_stats',), intercept=do_factors)
            if do_factors:
                csum = jax.tree.map(jnp.add, csum, contribs_of(captures))
            gsum = jax.tree.map(jnp.add, gsum, grads)
            return ({**extra_c, **updated}, gsum, csum), l

        gzero = jax.tree.map(jnp.zeros_like, params)
        czero = None
        if do_factors:
            csh = jax.eval_shape(
                lambda p: contribs_of(kfac.capture.loss_and_grads(
                    lambda out: loss(out, y), p, x, extra_vars=extra,
                    mutable_cols=('batch_stats',))[3]), params)
            czero = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                                 csh)
        (extra2, gsum, csum), ls = jax.lax.scan(
            micro, (extra, gzero, czero), (xs, ys))
        grads = jax.tree.map(lambda g: g / accum, gsum)
        if do_factors:
            # Micro-mean loss: g captures are accum x larger than the
            # global-mean-loss g; G is quadratic in g (the library's
            # g_fix in accum_fwd_bwd), plus the 1/accum contrib mean.
            from distributed_kfac_pytorch_tpu.ops import factors as F
            old = kst['factors']
            factors = {
                n: {'A': F.update_running_avg(
                        (c['A'] / accum).astype(old[n]['A'].dtype),
                        old[n]['A'], kfac.factor_decay),
                    'G': F.update_running_avg(
                        (c['G'] / accum ** 3).astype(old[n]['G'].dtype),
                        old[n]['G'], kfac.factor_decay)}
                for n, c in csum.items()}
            kst = {**kst, 'factors': factors}
        g, kst = kfac.step(kst, grads, {}, factor_update=False,
                           inv_update=False)
        updates, opt_state = tx.update(g, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state, kst, extra2), ls[-1]

    # Donated carry — same rationale as phase_step_leg above.
    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(carry):
        carry, losses = jax.lax.scan(body, carry, None, length=n_iters)
        return carry, losses[-1]

    carry0 = (params, opt_state, kstate, extra)
    floor = B.flops_floor_ms(kfac, variables, x, y,
                             mutable_cols=('batch_stats',)) * accum
    ms = B.time_chained(run, carry0, n_iters, floor_ms=floor, leg=mode)
    peak, _ = B.detected_tpu_peak()
    mfu = None
    if peak:
        flops = B.model_flops_per_step(kfac, params, x, y, extra) * accum
        mfu = round(flops / (ms * 1e-3) / peak, 4)
    return ms, mfu


def phase_firing(model_name, batch, image, n_firings, **kfac_kw):
    """Warm inverse firing over the model's real factor set (its own
    compiled program — no model fwd/bwd in it).

    Flagship factor sets have 4609-dim A factors whose fp32
    decompositions cost SECONDS per firing (resnet18: ~3.5 s measured),
    so the scan length stays small — a long-running single program
    trips the tunnel's execution limit (the 'UNAVAILABLE: TPU device
    error' failures recorded in round 3's first attempts)."""
    n_firings = min(n_firings, 3)
    (jax, jnp, optax, B, model, kfac, variables, kstate, x, y) = _setup(
        model_name, batch, image, **kfac_kw)
    # One real factor update so the decomposed matrices are covariance-
    # shaped, not the identity seed.
    _, _, grads, captures, _ = kfac.capture.loss_and_grads(
        lambda out: B.loss_fn(out, y), variables['params'], x,
        extra_vars={k: v for k, v in variables.items() if k != 'params'},
        mutable_cols=('batch_stats',))
    kstate = {**kstate, 'factors': kfac.update_factors(kstate, captures)}

    def body(state, _):
        new_inv = kfac.update_inverses(state, 0.003)
        # Chain: nudge factors so every firing decomposes new values
        # (and the warm path tracks, like training drift).
        factors = jax.tree.map(lambda f: f * (1.0 + 1e-5),
                               state['factors'])
        state = {**state, 'factors': factors, 'inverses': new_inv}
        probe = jax.tree.leaves(new_inv)[0].reshape(-1)[0]
        return state, probe

    @jax.jit
    def run(state):
        state, probes = jax.lax.scan(body, state, None, length=n_firings)
        return state, probes[-1]

    return B.time_chained(run, kstate, n_firings, repeats=2,
                          max_attempts=2)


def run_phase(args):
    kw = {}
    if args.bf16_factors:
        import jax.numpy as jnp
        kw = {'factor_dtype': jnp.bfloat16,
              'factor_compute_dtype': jnp.bfloat16}
    if args.bf16_inverses:
        import jax.numpy as jnp
        # Decompositions stay fp32 (the reference computes in fp32 and
        # stores in inv_dtype, which may be half precision — base.py:
        # 435-441); storage halves so the monolithic b128 remat capture
        # path fits HBM (the LM flagship's recipe at xl scale).
        kw['inv_dtype'] = jnp.bfloat16
    if args.inverse_method:
        kw['inverse_method'] = args.inverse_method
    if args.factor_batch_fraction is not None:
        kw['factor_batch_fraction'] = args.factor_batch_fraction
    if args.phase == 'firing':
        ms = phase_firing(args.model, args.batch, args.image, args.iters,
                          **kw)
        emit({'phase_result': round(ms, 2)})
    elif args.phase in ('accum_nofactor', 'accum_factors'):
        ms, mfu = phase_accum_leg(args.model, args.batch, args.image,
                                  args.phase, args.iters,
                                  accum=args.accum,
                                  model_dtype=args.model_dtype,
                                  remat=args.remat, **kw)
        emit({'phase_result': round(ms, 2), 'mfu': mfu})
    else:
        ms, mfu = phase_step_leg(args.model, args.batch, args.image,
                                 args.phase, args.iters,
                                 model_dtype=args.model_dtype,
                                 remat=args.remat, **kw)
        emit({'phase_result': round(ms, 2), 'mfu': mfu})


# ---------------------------------------------------------------------------
# Orchestrator
# ---------------------------------------------------------------------------

def spawn_phase(phase, model, batch, image, iters, bf16=False,
                inverse_method=None, model_dtype=None,
                factor_batch_fraction=None, remat=False, bf16_inv=False):
    cmd = [sys.executable, os.path.abspath(__file__), '--phase', phase,
           '--model', model, '--batch', str(batch), '--image', str(image),
           '--iters', str(iters)]
    if model_dtype:
        cmd += ['--model-dtype', model_dtype]
    if remat:
        cmd.append('--remat')
    if bf16:
        cmd.append('--bf16-factors')
    if bf16_inv:
        cmd.append('--bf16-inverses')
    if inverse_method:
        cmd += ['--inverse-method', inverse_method]
    if factor_batch_fraction is not None:
        cmd += ['--factor-batch-fraction', str(factor_batch_fraction)]
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=2400, cwd=REPO)
    except subprocess.TimeoutExpired:
        return 'failed: timeout', None
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            obj = json.loads(line)
            return obj['phase_result'], obj.get('mfu')
        except Exception:
            continue
    err = (out.stderr or '').strip().splitlines()
    return ('failed: ' + (err[-1][:120] if err else f'rc={out.returncode}'),
            None)


def config2(args):
    rows, mfus = {}, {}
    if args.reuse_legs:
        # 'sgd=16.03,precond=19.54,factors=31.28' from a prior recorded
        # run — each ~10 min of compile on the tunnel; they reproduced
        # within 1% across round-3 runs (no MFU fields for reused legs).
        rows = {k: float(v) for k, v in
                (kv.split('=') for kv in args.reuse_legs.split(','))}
        emit({'config': 2, 'reused_legs': rows})
    for mode in ('sgd', 'nofactor', 'precond', 'factors'):
        if mode in rows:
            continue
        rows[mode], mfus[mode] = spawn_phase(
            mode, args.model, args.batch, args.image, args.iters,
            model_dtype=args.model_dtype,
            factor_batch_fraction=args.factor_batch_fraction,
            remat=args.remat, bf16=args.bf16_factors,
            bf16_inv=args.bf16_inverses)
        emit({'config': 2, 'phase': mode, 'batch': args.batch,
              'image': args.image, 'remat': args.remat,
              'bf16_factors': args.bf16_factors,
              'bf16_inverses': args.bf16_inverses,
              'ms_per_iter': rows[mode], 'mfu': mfus.get(mode)})
    # The monolithic capture+factors+inverse program exceeds the compile
    # limit (tried each round; poisons the session) — the firing is
    # measured standalone instead, which IS the production execution
    # shape under static cadence. Per-method, 'auto' FIRST: the per-dim
    # dispatch is the out-of-the-box default (round 4), so the headline
    # composed row is the default config's; eigen/cholesky record the
    # endpoints the dispatch interpolates between.
    reused = {}
    if args.reuse_firing:
        reused = {k: float(v) for k, v in
                  (kv.split('=') for kv in args.reuse_firing.split(','))}
        bad = set(reused) - {'auto', 'cholesky', 'eigen'}
        if bad:
            raise SystemExit(f'--reuse-firing unknown method(s): {bad}')
        emit({'config': 2, 'reused_firings': reused})
    # Iteration below is canonical-order regardless of flag/reuse
    # spelling, preserving the auto-first invariant above.
    firings = {}
    for method in ('auto', 'cholesky', 'eigen'):
        if method in reused:
            firings[method] = reused[method]
            continue
        if method not in args.firing_methods:
            continue
        firings[method], _ = spawn_phase('firing', args.model, 8,
                                         args.image, args.iters,
                                         inverse_method=method,
                                         bf16=args.bf16_factors,
                                         bf16_inv=args.bf16_inverses)
        emit({'config': 2,
              'phase': f'inverse_firing_standalone_{method}',
              'ms_per_firing': firings[method]})

    methods = [(m, v) for m, v in firings.items()
               if isinstance(v, (int, float))]
    if all(isinstance(v, (int, float)) for k, v in rows.items()
           if k != 'nofactor') and methods:
        # Composition: 1/f of steps run the full factor step (capture +
        # EWMA + precond), the rest run the plain non-factor step
        # (intercept=False — capture gated off like the reference's
        # _periodic_hook). factor_step_extra therefore includes the
        # capture cost, which is only paid on factor steps. A failed
        # 'nofactor' leg (tunnel flake) falls back to the capturing
        # 'precond' leg — conservative (over-counts the non-factor
        # steps) rather than suppressing the composed rows.
        base = rows['nofactor'] if isinstance(
            rows.get('nofactor'), (int, float)) else rows['precond']
        factor_cost = max(rows['factors'] - base, 0.0)
        for fire_method, fire_ms in methods:
            # row_schema 2 (round 4+): 'every_iter' is the capture-free
            # nofactor leg (the old capturing value moved to
            # 'every_iter_capturing') and 'factor_cost' was renamed
            # 'factor_step_extra'. Schema-less rows are round-3
            # (schema 1) semantics — cross-round comparisons must key
            # on this field (ADVICE r4).
            out = {'config': 2, 'row_schema': 2,
                   'workload': (f'{args.model}_imagenet{args.image}'
                                f'_b{args.batch}'
                                + ('_remat' if args.remat else '')
                                + ('_bf16state' if args.bf16_factors
                                   or args.bf16_inverses else '')),
                   'bf16_factors': args.bf16_factors,
                   'bf16_inverses': args.bf16_inverses,
                   'unit': 'ms/iter', 'sgd': rows['sgd'],
                   'mfu_sgd': mfus.get('sgd'),
                   'every_iter': base,
                   'every_iter_capturing': rows.get('precond'),
                   'factor_step_extra': round(factor_cost, 2),
                   'inv_firing_method': fire_method,
                   'inv_firing_ms': round(fire_ms, 2)}
            for label, f, i in (('stress_f1_i10', 1, 10),
                                ('imagenet_default_f10_i100', 10, 100),
                                ('production_f50_i500', 50, 500)):
                total = base + factor_cost / f + fire_ms / i
                out[label] = round(total, 2)
                out[label + '_vs_sgd'] = round(total / rows['sgd'], 3)
                # Model-math MFU at this cadence: flops fixed per step,
                # so mfu scales as sgd_ms/total from the SGD leg's MFU.
                if mfus.get('sgd'):
                    out[label + '_mfu'] = round(
                        mfus['sgd'] * rows['sgd'] / total, 4)
            emit(out)
    else:
        emit({'config': 2, 'workload': args.model, 'partial': rows,
              'firings': firings})


def config5(args):
    """ResNet-152 full factor set through the real decomposition path,
    bf16 factors + fp32 eigendecomp (BASELINE config 5). 64px input:
    factor dims depend on channel/kernel structure only."""
    # inverse_method='eigen' explicitly: this config tracks the fp32
    # EIGENDECOMPOSITION cost series across rounds — the round-4 'auto'
    # default would silently send the >640-dim factors to cholesky and
    # corrupt the baseline series under the same label.
    firing, _ = spawn_phase('firing', 'resnet152', 4, 64, args.iters,
                            bf16=True, inverse_method='eigen')
    emit({'config': 5,
          'workload': 'resnet152_full_factor_set_bf16_fp32eigh',
          'decomposition_firing_ms': firing})
    factors, _ = spawn_phase('factors', 'resnet152', 4, 64, args.iters,
                             bf16=True)
    emit({'config': 5, 'phase': 'factors_b4_64px',
          'ms_per_iter': factors})


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--iters', type=int, default=20)
    p.add_argument('--batch', type=int, default=32)
    p.add_argument('--image', type=int, default=176)
    p.add_argument('--model', default='resnet50')
    p.add_argument('--configs', type=int, nargs='+', default=[2, 5])
    p.add_argument('--phase', default=None,
                   help='internal: run a single measurement leg')
    p.add_argument('--accum', type=int, default=2,
                   help='micro-batches per optimizer step for the '
                        'accum_* phases (batch is the MICRO batch; '
                        'the leg is b{batch*accum}-equivalent)')
    p.add_argument('--bf16-factors', action='store_true')
    p.add_argument('--bf16-inverses', action='store_true',
                   help='bf16 inverse storage (inv_dtype; decompositions '
                        'stay fp32) — halves K-FAC state so the '
                        'monolithic b128 remat capture path fits HBM')
    p.add_argument('--remat', action='store_true',
                   help='block-level gradient checkpointing on the '
                        'model (fits monolithic b128+ @224 bf16 with '
                        'K-FAC capture; round-5 study)')
    p.add_argument('--model-dtype', default=None,
                   choices=['fp32', 'bf16'],
                   help='model compute dtype for the step legs; bf16 = '
                        "the reference fp16 production recipe's TPU "
                        'analogue (and what fits b128 @ 224px in HBM)')
    p.add_argument('--inverse-method', default=None,
                   choices=['auto', 'eigen', 'cholesky', 'newton'])
    p.add_argument('--factor-batch-fraction', type=float, default=None,
                   help='opt-in within-step factor-statistic thinning '
                        'for the step legs (KFAC.factor_batch_fraction)')
    p.add_argument('--reuse-legs', default=None,
                   help="e.g. 'sgd=16.03,precond=19.54,factors=31.28' "
                        'from a prior recorded run')
    p.add_argument('--firing-methods', nargs='+',
                   default=['auto', 'cholesky', 'eigen'],
                   choices=['auto', 'cholesky', 'eigen'],
                   help='inverse-firing legs to measure; the firing is '
                        'remat/batch-independent, so sessions that vary '
                        'only those can pass just "auto" (~10 min '
                        'compile saved per skipped method)')
    p.add_argument('--reuse-firing', default=None,
                   help="e.g. 'auto=131.9' ms from a prior recorded "
                        'run of the SAME factor set — composition rows '
                        'use it without re-measuring')
    args = p.parse_args(argv)
    if args.phase:
        run_phase(args)
        return
    if 2 in args.configs:
        config2(args)
    if 5 in args.configs:
        config5(args)


if __name__ == '__main__':
    main()
