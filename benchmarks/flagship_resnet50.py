"""Flagship on-chip numbers: ResNet-50 (config 2) and ResNet-152-class
decompositions (config 5) — the BASELINE.md rows that previously had no
recorded on-chip measurement (round-2 verdict, Missing #1).

The tunneled dev chip drops oversized programs (PERF.md "Known infra
limits"): one monolithic ResNet-50 K-FAC train step exceeds the
remote-compile size limit. Cadence is already *static program
structure* in this framework, so the step decomposes into separately
compiled scanned programs per phase — each measured on the real chip,
composed into per-cadence totals:

  sgd        fwd+bwd+momentum                        (batch 64, 176px)
  precond    + capture + precondition + KL clip      (every-iter work)
  factors    + factor EWMA                           (factor-step work)
  inv        + inverse updates every iter (batch 8 — decomposition cost
             is batch-independent; measured as the per-firing delta)

  total(f, i) = precond + (factors - precond)/f + firing/i

Reference cadences composed: stress (1, 10), ImageNet default (10, 100
— torch_imagenet_resnet.py:75-78), production (50, 500 —
launch_node_torch_imagenet.sh:73-87).

Config 5: ResNet-152's full factor set (bf16 factors + fp32
decompositions, BASELINE.md config 5) pushed through the real bucketed
batched decomposition path, timed per firing.

Any phase whose program still exceeds the compile limit is reported as
'compile_failed' rather than silently substituted (the round-2 verdict
critique of bench_matrix's silent resnet18 fallback).

    python benchmarks/flagship_resnet50.py [--iters 30] [--image 176]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench as B  # noqa: E402
from distributed_kfac_pytorch_tpu import KFAC  # noqa: E402
from distributed_kfac_pytorch_tpu.models import imagenet_resnet  # noqa: E402


def emit(obj):
    print(json.dumps(obj), flush=True)


def build_leg(model, x, y, mode, inv_every_iter=False):
    """One scanned runner. Modes: sgd | precond | factors | inv."""
    kfac = KFAC(model, factor_update_freq=1, inv_update_freq=1,
                damping=0.003, lr=0.1)
    variables, kstate = kfac.init(jax.random.PRNGKey(0), x)
    params = variables['params']
    extra = {k: v for k, v in variables.items() if k != 'params'}
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    def loss(out):
        return B.loss_fn(out, y)

    if mode == 'sgd':
        def body(carry, _):
            params, opt_state, extra = carry

            def wrapped(p):
                out, updated = model.apply({'params': p, **extra}, x,
                                           mutable=['batch_stats'])
                return loss(out), updated
            (l, updated), grads = jax.value_and_grad(
                wrapped, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, {**extra, **updated}), l
        carry0 = (params, opt_state, extra)
    else:
        flags = {'sgd': None,
                 'precond': (False, False),
                 'factors': (True, False),
                 'inv': (True, True)}[mode]

        def body(carry, _):
            params, opt_state, kstate, extra = carry
            l, _, grads, captures, updated = kfac.capture.loss_and_grads(
                loss, params, x, extra_vars=extra,
                mutable_cols=('batch_stats',))
            g, kstate = kfac.step(kstate, grads, captures,
                                  factor_update=flags[0],
                                  inv_update=flags[1])
            updates, opt_state = tx.update(g, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, kstate, {**extra, **updated}), l
        carry0 = (params, opt_state, kstate, extra)

    def run_factory(n_iters):
        @jax.jit
        def run(carry):
            carry, losses = jax.lax.scan(body, carry, None,
                                         length=n_iters)
            return carry, losses[-1]
        return run

    floor = B.flops_floor_ms(kfac, variables, x, y,
                             mutable_cols=('batch_stats',))
    return run_factory, carry0, floor


def time_leg(model, x, y, mode, n_iters, floor_scale=1.0):
    run_factory, carry0, floor = build_leg(model, x, y, mode)
    run = run_factory(n_iters)
    try:
        ms = B.time_chained(run, carry0, n_iters,
                            floor_ms=floor * floor_scale, leg=mode)
        return round(ms, 2)
    except Exception as e:
        msg = str(e)
        if 'response body' in msg or 'compile' in msg.lower() or \
                'RESOURCE_EXHAUSTED' in msg:
            return f'compile_failed: {type(e).__name__}'
        raise


def inverse_firing_standalone(model, x, y, n_firings):
    """ms per warm inverse firing over the model's REAL factor set,
    timed as its own compiled program (no model fwd/bwd in it)."""
    kfac = KFAC(model, factor_update_freq=1, inv_update_freq=1,
                damping=0.003, lr=0.1)
    variables, kstate = kfac.init(jax.random.PRNGKey(0), x)
    # One real factor update so the decomposed matrices are covariance-
    # shaped, not the identity seed.
    _, _, grads, captures, _ = kfac.capture.loss_and_grads(
        lambda out: B.loss_fn(out, y), variables['params'], x,
        extra_vars={k: v for k, v in variables.items()
                    if k != 'params'},
        mutable_cols=('batch_stats',))
    kstate = {**kstate,
              'factors': kfac.update_factors(kstate, captures)}

    def body(state, _):
        new_inv = kfac.update_inverses(state, 0.003)
        # Chain: nudge factors so every firing decomposes new values
        # (and the warm path tracks, like training drift).
        factors = jax.tree.map(lambda f: f * (1.0 + 1e-5),
                               state['factors'])
        state = {**state, 'factors': factors, 'inverses': new_inv}
        probe = jax.tree.leaves(new_inv)[0].reshape(-1)[0]
        return state, probe

    @jax.jit
    def run(state):
        state, probes = jax.lax.scan(body, state, None,
                                     length=n_firings)
        return state, probes[-1]

    try:
        return round(B.time_chained(run, kstate, n_firings), 2)
    except Exception as e:
        return f'failed: {type(e).__name__}'


def config2(args):
    model = imagenet_resnet.get_model(args.model)
    img = args.image
    x = jax.random.normal(jax.random.PRNGKey(1), (args.batch, img, img, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (args.batch,), 0, 1000)
    n = args.iters
    rows = {}
    for mode in ('sgd', 'precond', 'factors'):
        rows[mode] = time_leg(model, x, y, mode, n)
        emit({'config': 2, 'phase': mode, 'batch': args.batch,
              'image': img, 'ms_per_iter': rows[mode]})

    # Inverse firing cost at small batch (decomposition cost is factor-
    # dim-bound, not batch-bound): firing = inv-every-iter minus
    # factors-every-iter at the same small batch.
    xs = jax.random.normal(jax.random.PRNGKey(1), (8, img, img, 3))
    ys = jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 1000)
    small = {}
    for mode in ('factors', 'inv'):
        small[mode] = time_leg(model, xs, ys, mode, n)
        emit({'config': 2, 'phase': f'{mode}_b8',
              'ms_per_iter': small[mode]})

    if not isinstance(small.get('inv'), (int, float)):
        # The capture+factors+inverse program is the one that exceeds
        # the tunnel's compile-size limit. The decomposition pipeline is
        # cadence-gated static program structure, so timing it as its
        # own compiled program IS the production execution shape: scan
        # chained update_inverses firings (warm path, factors nudged per
        # firing) over the real ResNet-50 factor set.
        firing_ms = inverse_firing_standalone(model, xs, ys, n)
        emit({'config': 2, 'phase': 'inverse_firing_standalone',
              'ms_per_firing': firing_ms})
        if isinstance(firing_ms, (int, float)):
            small['inv'] = small.get('factors', 0) + firing_ms \
                if isinstance(small.get('factors'), (int, float)) else None
            if small['inv'] is None:
                small.pop('inv')

    numeric = all(isinstance(v, (int, float)) for v in rows.values())
    if numeric and all(isinstance(v, (int, float))
                       for v in small.values()) and 'inv' in small:
        firing = max(small['inv'] - small['factors'], 0.0)
        factor_cost = max(rows['factors'] - rows['precond'], 0.0)
        out = {'config': 2, 'workload': f'{args.model}_imagenet{img}'
                                        f'_b{args.batch}',
               'unit': 'ms/iter', 'sgd': rows['sgd'],
               'inv_firing_ms': round(firing, 2)}
        for label, f, i in (('stress_f1_i10', 1, 10),
                            ('imagenet_default_f10_i100', 10, 100),
                            ('production_f50_i500', 50, 500)):
            total = rows['precond'] + factor_cost / f + firing / i
            out[label] = round(total, 2)
            out[label + '_vs_sgd'] = round(total / rows['sgd'], 3)
        emit(out)
    else:
        emit({'config': 2, 'workload': f'{args.model}', 'partial': rows,
              'small_batch': small})


def config5(args):
    """ResNet-152 factor set through the real decomposition path,
    bf16 factors + fp32 eigendecomp (BASELINE config 5)."""
    model = imagenet_resnet.get_model('resnet152')
    # 64px input: factor dims depend on channel/kernel structure only;
    # small spatial keeps the capture fwd/bwd cheap so the measured
    # delta is the decomposition pipeline.
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 64, 64, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (4,), 0, 1000)
    n = args.iters
    kfac = KFAC(model, factor_update_freq=1, inv_update_freq=1,
                damping=0.003, lr=0.1, factor_dtype=jnp.bfloat16,
                factor_compute_dtype=jnp.bfloat16)
    dims = {}
    variables, kstate = kfac.init(jax.random.PRNGKey(0), x)
    for name, st in kstate['factors'].items():
        for which in ('A', 'G'):
            d = st[which].shape[-1] if st[which].ndim else 1
            dims[d] = dims.get(d, 0) + 1
    emit({'config': 5, 'model': 'resnet152',
          'n_factors': sum(dims.values()),
          'factor_dim_histogram': {str(k): v for k, v in
                                   sorted(dims.items())}})

    params = variables['params']
    extra = {k: v for k, v in variables.items() if k != 'params'}
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    def make_body(inv_update):
        def body(carry, _):
            params, opt_state, kstate, extra = carry
            l, _, grads, captures, updated = kfac.capture.loss_and_grads(
                lambda out: B.loss_fn(out, y), params, x,
                extra_vars=extra, mutable_cols=('batch_stats',))
            g, kstate = kfac.step(kstate, grads, captures,
                                  factor_update=True,
                                  inv_update=inv_update)
            updates, opt_state = tx.update(g, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, kstate, {**extra, **updated}), l
        return body

    carry0 = (params, opt_state, kstate, extra)
    out = {}
    for label, inv in (('factors_only', False), ('with_inverse', True)):
        @jax.jit
        def run(carry, body=make_body(inv)):
            carry, losses = jax.lax.scan(body, carry, None, length=n)
            return carry, losses[-1]
        try:
            out[label] = round(B.time_chained(run, carry0, n), 2)
        except Exception as e:
            out[label] = f'failed: {type(e).__name__}'
        emit({'config': 5, 'phase': label, 'ms_per_iter': out[label]})
    if all(isinstance(v, (int, float)) for v in out.values()):
        emit({'config': 5,
              'workload': 'resnet152_full_factor_set_bf16_fp32eigh',
              'decomposition_firing_ms': round(
                  out['with_inverse'] - out['factors_only'], 2)})


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--iters', type=int, default=30)
    p.add_argument('--batch', type=int, default=64)
    p.add_argument('--image', type=int, default=176)
    p.add_argument('--model', default='resnet50')
    p.add_argument('--configs', type=int, nargs='+', default=[2, 5])
    args = p.parse_args(argv)
    if 2 in args.configs:
        config2(args)
    if 5 in args.configs:
        config5(args)


if __name__ == '__main__':
    main()
