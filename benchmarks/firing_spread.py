"""Step-time histogram bench for pipelined inverse firing (r9).

Measures the thing the tentpole changes: the per-step wall-time
DISTRIBUTION of a K-FAC run at stress cadence, monolithic vs pipelined
(``inv_pipeline_chunks = k``). The tracked config-4 LM fires its whole
inverse update on one step of each cadence window — a 4x step-time
outlier on the xl flagship (PERF.md r5: 531.8 ms firing vs 129.2
non-factor) that sets p99 on one chip and is a synchronous straggler
on a mesh. Pipelining fires cost-balanced chunks across the window
instead; the claim under test is structural (spike height vs median),
so the CPU backend suffices per PERF.md r6 conventions — absolute ms
are NOT v5e-comparable and the on-chip re-run is owed (r9 decision
rule in PERF.md).

Per ``k`` leg: build the config-4 transformer LM (CPU-scaled size by
default), run the production ``DistributedKFAC.build_train_step`` +
``engine.train_epoch`` path with a metrics sink at interval 1, and
summarize the recorded stream with the r9
``observability.report.step_time_distribution`` section (p50/p95/p99/
max + fired-stage outlier attribution) — the bench's output IS the
report's percentile section, not a parallel implementation.

Timing note: each step is closed with ``block_until_ready`` so
``host_step_ms`` is the true per-step wall time attributed to the step
that ran it (async dispatch would smear a firing's cost into the next
step's record). That makes this a *distribution* bench, not a
throughput bench — bench.py's chained-scan methodology remains the
authority for ms/iter claims.

    python benchmarks/firing_spread.py [--size tiny] [--chunks 1 2 4]
        [--inv-update-freq 8] [--windows 6] [--out BENCH_...json]
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def emit(obj):
    print(json.dumps(obj), flush=True)


def run_leg(args, k: int, kfac_extra: dict | None = None,
            label: str | None = None) -> dict:
    import jax
    import jax.numpy as jnp
    import optax

    from distributed_kfac_pytorch_tpu import KFAC
    from distributed_kfac_pytorch_tpu.models import transformer_lm
    from distributed_kfac_pytorch_tpu.observability import report
    from distributed_kfac_pytorch_tpu.observability import sink as osink
    from distributed_kfac_pytorch_tpu.parallel import distributed as D
    from distributed_kfac_pytorch_tpu.training import engine

    i_freq = args.inv_update_freq
    overrides = {}
    if args.d_model:
        overrides = dict(d_model=args.d_model,
                         num_layers=args.num_layers,
                         num_heads=args.num_heads)
    model = transformer_lm.get_model(vocab_size=args.vocab,
                                     size=args.size, max_len=args.seq,
                                     dropout=0.0, **overrides)
    kfac = KFAC(model, factor_update_freq=args.factor_update_freq,
                inv_update_freq=i_freq, damping=0.003, lr=0.1,
                inverse_method=args.inverse_method or None,
                inv_pipeline_chunks=k, **(kfac_extra or {}))
    ids = jax.random.randint(jax.random.PRNGKey(1),
                             (args.batch, args.seq), 0, args.vocab)
    tgt = jax.random.randint(jax.random.PRNGKey(2),
                             (args.batch, args.seq), 0, args.vocab)
    variables, _ = kfac.init(jax.random.PRNGKey(0), ids, train=False)
    params = variables['params']
    mesh = D.make_kfac_mesh(jax.devices()[:1])
    dkfac = D.DistributedKFAC(kfac, mesh, params)
    kstate = dkfac.init_state(params)
    tx = optax.sgd(0.1, momentum=0.9)

    def loss_fn(out, batch):
        logits = out[0] if isinstance(out, tuple) else out
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch[1]).mean()

    raw_step = dkfac.build_train_step(
        loss_fn, tx, model_args_fn=lambda b: (b[0],),
        model_kwargs_fn=lambda b: {'train': False})

    @functools.wraps(raw_step)
    def step(*a, **kw):
        out = raw_step(*a, **kw)
        jax.block_until_ready(out)  # exact per-step attribution
        return out

    step.inv_pipeline_chunks = raw_step.inv_pipeline_chunks
    step.trace_counts = raw_step.trace_counts

    hyper = {'lr': 0.1, 'damping': 0.003,
             'factor_update_freq': args.factor_update_freq,
             'inv_update_freq': i_freq}
    state = engine.TrainState(params, tx.init(params), kstate, {})
    batch = (ids, tgt)
    # Warmup epoch: compiles every variant (step-0 warmup firing, each
    # chunk phase, the plain step) and runs one steady window, so the
    # timed epoch re-executes compiled programs only.
    engine.train_epoch(step, state, [batch] * (2 * i_freq), hyper)
    n_timed = args.windows * i_freq
    mpath = os.path.join(args.metrics_dir,
                         f'firing_spread_{label or f"k{k}"}.jsonl')
    sink = osink.JsonlMetricsSink(mpath, interval=1)
    engine.train_epoch(step, state, [batch] * n_timed, hyper,
                       metrics_sink=sink)
    sink.close()
    records = osink.read_jsonl(mpath)
    dist = report.step_time_distribution(records)
    # Per-window inverse cost: excess over the NON-FIRING step median
    # across every firing step (inverse or chunk), averaged over the
    # timed windows — the "total per-window inverse ms within 10% of
    # monolithic" acceptance term. The global p50 would be the wrong
    # baseline here: at stride <= 2 (e.g. k=4 over an 8-step window)
    # half the steps fire a chunk, the global median absorbs firing
    # cost, and excess-over-p50 silently undercounts the pipelined
    # legs. The report's percentile section keeps the global
    # distribution (that IS the step-time-uniformity product); this
    # baseline is only for the cross-leg work accounting.
    def is_firing(r):
        fired = str(r.get('fired', ''))
        return (r.get('kind') == 'step'
                and (fired == 'inverse' or fired.startswith('chunk')))

    plain = sorted(r['host_step_ms'] for r in records
                   if r.get('kind') == 'step' and not is_firing(r))
    # stride 1 (k == inv_update_freq) fires a chunk on EVERY step —
    # no plain steps exist; fall back to the global p50 (all steps are
    # then drawn from the same chunk-firing mixture anyway).
    plain_med = (plain[len(plain) // 2] if plain else dist['p50_ms'])
    fire_excess = sum(r['host_step_ms'] - plain_med
                      for r in records if is_firing(r))
    retraced = {str(key): n for key, n in step.trace_counts.items()
                if n != 1}
    assert not retraced, f'variants retraced during the bench: {retraced}'
    return {
        'leg': label or f'k{k}',
        'inv_pipeline_chunks': k,
        'n_timed_steps': n_timed,
        'windows': args.windows,
        'plain_median_ms': round(plain_med, 2),
        'window_inverse_ms': round(fire_excess / args.windows, 2),
        # The residual spike over a plain step — the uniformity number
        # free of the mixture-median artifact above (at k=4 half the
        # steps fire, so the global max/median is flattered by the
        # median shifting up, not only by the spike shrinking).
        'max_over_plain_median': round(dist['max_ms'] / plain_med, 3),
        'step_time': {key: (round(v, 3) if isinstance(v, float) else v)
                      for key, v in dist.items() if key != 'stages'},
        'stages': dist['stages'],
        'variants_compiled': len(step.trace_counts),
        'metrics_jsonl': mpath,
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--size', default='small',
                   help='transformer size name (overridden by '
                        '--d-model); run xl on a real chip')
    p.add_argument('--d-model', type=int, default=512,
                   help='CPU-scaled config-4 default: d512 keeps the '
                        'FFN factor dims (2048/2049) in the COMPUTE-'
                        'bound cholesky regime where firing cost '
                        'scales linearly with chunk content; at tiny '
                        'dims the firing is latency-bound and '
                        'chunking cannot smear it (measured on this '
                        'backend). 0 = use --size as-is')
    p.add_argument('--num-layers', type=int, default=8,
                   help='CPU-scaled default 8: the FFN dim buckets '
                        'then hold 8 same-dim matrices each, so a '
                        'k<=4 chunk still decomposes its share as a '
                        'BATCHED call — measured on this backend, a '
                        'batch-1 cholesky at dim 3072 pays ~170 ms '
                        'per-call overhead (+13%%) over its batch-share '
                        'in a batch-4 call, which would masquerade as '
                        'pipelining overhead in the within-10%% '
                        'window-cost term')
    p.add_argument('--num-heads', type=int, default=8)
    p.add_argument('--inverse-method', default='cholesky',
                   help="'cholesky' default: the flagship xl firing "
                        'is all-Cholesky (its dims sit above the 640 '
                        'eigen cutoff), and cholesky cost scales '
                        'linearly with chunk content on every backend')
    p.add_argument('--seq', type=int, default=32)
    p.add_argument('--batch', type=int, default=2)
    p.add_argument('--vocab', type=int, default=1024)
    p.add_argument('--factor-update-freq', type=int, default=1,
                   help='stress cadence default (factors every step)')
    p.add_argument('--inv-update-freq', type=int, default=8,
                   help='cadence window; every --chunks entry must '
                        'divide it (8 = the nearest chunk-divisible '
                        'stress cadence to the tracked i10)')
    p.add_argument('--chunks', type=int, nargs='+', default=[1, 2, 4])
    p.add_argument('--lowrank', action='store_true',
                   help='r19 randomized low-rank A/B instead of the '
                        'chunk sweep: one exact leg and one with '
                        '--lowrank-rank engaged on every dim >= '
                        '--lowrank-dim-threshold (both monolithic '
                        'k=1), emitting the per-window inverse-cost '
                        'ratio — the "decomposition cost reduced '
                        '>= 3x" acceptance number (PERF.md r19)')
    p.add_argument('--lowrank-rank', type=int, default=64,
                   help='--lowrank truncation rank')
    p.add_argument('--lowrank-dim-threshold', type=int, default=1024,
                   help='--lowrank engagement threshold (the CPU-'
                        'scaled config-4 d512 ladder engages its '
                        '2048/2049 FFN dims at the default)')
    p.add_argument('--windows', type=int, default=6,
                   help='timed cadence windows per leg')
    p.add_argument('--metrics-dir', default=None)
    p.add_argument('--out', default=None,
                   help='write header+legs to this BENCH artifact '
                        '(overwrites — one invocation produces one '
                        'self-consistent artifact; run all chunk legs '
                        'in a single invocation)')
    args = p.parse_args(argv)
    if args.metrics_dir is None:
        args.metrics_dir = tempfile.mkdtemp(prefix='firing_spread_')
    os.makedirs(args.metrics_dir, exist_ok=True)

    import jax
    rows = []
    header = {
        'bench': ('firing_spread_lowrank' if args.lowrank
                  else 'firing_spread'),
        'workload': (f'transformer_lm_{args.size}'
                     + (f'_d{args.d_model}L{args.num_layers}'
                        if args.d_model else '')
                     + f'_seq{args.seq}_b{args.batch}_v{args.vocab}'),
        'cadence': {'factor_update_freq': args.factor_update_freq,
                    'inv_update_freq': args.inv_update_freq},
        'backend': jax.default_backend(),
        'note': ('structural step-time-uniformity claim; absolute ms '
                 'are backend-local (PERF.md r6 CPU conventions), '
                 'on-chip re-run owed per PERF.md r9 decision rule'),
    }
    if args.lowrank:
        header['lowrank'] = {'rank': args.lowrank_rank,
                             'dim_threshold': args.lowrank_dim_threshold}
    emit(header)

    if args.lowrank:
        exact = run_leg(args, 1, label='exact')
        emit(exact)
        rows.append(exact)
        low = run_leg(args, 1, label='lowrank', kfac_extra=dict(
            inv_lowrank_rank=args.lowrank_rank,
            inv_lowrank_dim_threshold=args.lowrank_dim_threshold))
        low['inv_lowrank_rank'] = args.lowrank_rank
        low['inv_lowrank_dim_threshold'] = args.lowrank_dim_threshold
        if low['window_inverse_ms'] > 0:
            low['decomposition_cost_ratio'] = round(
                exact['window_inverse_ms'] / low['window_inverse_ms'],
                2)
        emit(low)
        rows.append(low)
        if args.out:
            with open(args.out, 'w') as f:
                json.dump({'header': header, 'legs': rows}, f, indent=1)
            print(f'wrote {args.out}', file=sys.stderr)
        return 0

    baseline = None
    for k in args.chunks:
        row = run_leg(args, k)
        if k == 1:
            baseline = row
        if baseline is not None and k != 1:
            row['vs_monolithic'] = {
                'max_over_median_ratio': round(
                    baseline['step_time']['max_over_median']
                    / row['step_time']['max_over_median'], 2),
                'max_over_plain_median_ratio': round(
                    baseline['max_over_plain_median']
                    / row['max_over_plain_median'], 2),
                'window_inverse_ms_ratio': round(
                    row['window_inverse_ms']
                    / max(baseline['window_inverse_ms'], 1e-9), 3),
            }
        emit(row)
        rows.append(row)
    if args.out:
        with open(args.out, 'w') as f:
            json.dump({'header': header, 'legs': rows}, f, indent=1)
        print(f'wrote {args.out}', file=sys.stderr)
    return 0


if __name__ == '__main__':
    sys.exit(main())
