"""Grouped-collective microbenchmark over the K-FAC mesh.

TPU-native counterpart of the reference's distributed comm benchmark
(tests/communication.py:13-57 + launch scripts): for every divisor group
size of the device count it times the collectives the K-FAC pipeline
actually issues — ``psum`` over the full mesh (factor allreduce), the
``all_gather`` over the grad-worker axis (inverse broadcast), and the
``psum`` over the inverse-group axis (gradient broadcast) — using the
``@trace`` utility (reference kfac/utils.py:8-56).

Run on any topology (virtual CPU mesh, single chip, pod):
    python benchmarks/communication.py [--size 100] [--iters 20]
"""

from __future__ import annotations

import argparse
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from distributed_kfac_pytorch_tpu import utils
from distributed_kfac_pytorch_tpu.parallel.distributed import (
    GRAD_WORKER_AXIS,
    INV_GROUP_AXIS,
    KFAC_AXES,
)


def divisors(n: int) -> list[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def bench_group_size(devices, grad_workers: int, size: int, iters: int):
    n = len(devices)
    mesh = Mesh(np.asarray(devices).reshape(n // grad_workers,
                                            grad_workers), KFAC_AXES)
    x = jnp.ones((size, size), jnp.float32)

    def make(op):
        fn = jax.jit(jax.shard_map(
            op, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False))
        fn(x)  # compile
        return fn

    ops = {
        f'allreduce_world[gw={grad_workers}]':
            make(lambda v: jax.lax.psum(v, KFAC_AXES) / n),
        f'gather_inv_group[gw={grad_workers}]':
            make(lambda v: jax.lax.all_gather(v, GRAD_WORKER_AXIS,
                                              tiled=True)),
        f'bcast_grad_group[gw={grad_workers}]':
            make(lambda v: jax.lax.psum(v, INV_GROUP_AXIS)),
    }
    for name, fn in ops.items():
        timed = utils.trace(sync=True, name=name)(fn)
        for _ in range(iters):
            timed(x)


def run_multihost(out_path: str) -> None:
    """Spawn the 2-process gloo benchmarks (tests/multihost_worker.py
    'comm' + 'comm_flagship' modes) and record COMM_MULTIHOST.json —
    grouped-collective timings with the KAISA grad-worker axis laid out
    within vs across the process boundary (the ICI-vs-DCN placement
    evidence for the MEM/HYBRID tradeoff; VERDICT r2 #10), at both the
    reference's 256^2 probe size and the round-4 flagship factor dims.
    Both sections are regenerated together so a rerun never silently
    drops one (round-4 review finding)."""
    import json
    import socket
    import subprocess
    import tempfile

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, 'tests', 'multihost_worker.py')
    out_path = os.path.abspath(out_path)
    env = {**os.environ, 'PYTHONPATH': repo}
    results = {}
    for mode in ('comm', 'comm_flagship', 'comm_hier'):
        with socket.socket() as s:
            s.bind(('localhost', 0))
            port = s.getsockname()[1]
        with tempfile.NamedTemporaryFile(suffix='.json') as tmp:
            procs = [subprocess.Popen(
                [sys.executable, worker, str(port), str(pid), '2',
                 tmp.name, mode], cwd=repo, env=env)
                for pid in range(2)]
            try:
                rcs = [proc.wait(timeout=600) for proc in procs]
            finally:
                for proc in procs:
                    if proc.poll() is None:
                        proc.kill()  # don't strand the rendezvous peer
            if any(rcs):
                raise RuntimeError(f'{mode}: worker exit codes {rcs}')
            with open(tmp.name) as f:
                results[mode] = json.load(f)
    merged = dict(results['comm'])
    merged['flagship_dims'] = {
        'note': ('per-phase grouped collectives at ResNet-50 factor '
                 'dims (85 MB 4609^2 factor pmean, 4x1153^2 inverse '
                 'gather over kfac_gw, 2048x2049 grad psum over '
                 'kfac_ig); single-box gloo stand-in — the recorded '
                 'evidence is correctness cross-process at flagship '
                 'sizes and the per-phase cost ordering, not the real '
                 'ICI/DCN asymmetry'),
        'gw_intra_process': results['comm_flagship']['gw_intra_process'],
        'gw_cross_process': results['comm_flagship']['gw_cross_process'],
    }
    merged['hierarchical'] = {
        'note': ('r20 two-level factor reduction on a 2-slice nested '
                 'mesh whose slice boundary is the process boundary '
                 '(gloo = DCN stand-in): flat = one global pmean per '
                 'factor step; hierarchical = on-slice pmean per step '
                 '+ one cross-slice pmean per r14 window. Decision '
                 'rule (PERF.md r20): hierarchical wins a W-step '
                 'window when W*intra + dcn < W*flat'),
        'slice_per_process': results['comm_hier']['slice_per_process'],
    }
    with open(out_path, 'w') as f:
        json.dump(merged, f, indent=1)
    print(json.dumps(merged))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--size', type=int, default=100,
                   help='square tensor edge (reference: 100x100)')
    p.add_argument('--iters', type=int, default=20)
    p.add_argument('--multihost', action='store_true',
                   help='spawn the 2-process gloo cross-boundary '
                        'benchmark instead (writes --out)')
    p.add_argument('--out', default='COMM_MULTIHOST.json')
    args = p.parse_args(argv)

    if args.multihost:
        run_multihost(args.out)
        return

    devices = jax.devices()
    print(f'{len(devices)} devices ({jax.default_backend()}); '
          f'tensor {args.size}x{args.size}; {args.iters} iters')
    utils.clear_trace()
    for gw in divisors(len(devices)):
        bench_group_size(devices, gw, args.size, args.iters)
    utils.print_trace(average=True)


if __name__ == '__main__':
    main()
