"""Per-phase breakdown of the tracked-config K-FAC step (on-chip).

Times cumulative program variants of the bench.py workload (ResNet-32 /
CIFAR-10, batch 512, reference CIFAR cadence) so the per-phase cost of
every pipeline stage is a recorded number, not an inference:

  sgd            plain SGD step (fwd+bwd+momentum)
  capture        fwd+bwd through the K-FAC capture machinery, SGD update
                 (isolates the interception cost vs plain value_and_grad)
  precond        + preconditioning with frozen inverses + KL clip
                 (factor_update=False, inv_update=False)
  factors        + factor EWMA every iter (factor_update=True)
  factors_deferred  the 'factors' phase under r14 deferred reduction:
                 per-iter local accumulation, the EWMA boundary update
                 once per ``inv_freq`` window (single-chip: the delta
                 vs 'factors' is the accumulate-vs-EWMA program cost —
                 the collective saving only exists on a mesh)
  full           + amortized inverse updates every ``inv_freq`` iters
  full_polishN   full with eigh_polish_iters=N variants
  precond_bf16   the 'precond' phase with precond_compute_dtype=bf16
                 (r6 A/B: attributes the every-step precondition tax
                 per contraction dtype)

The phase cost is the difference between adjacent rows; the rows are
cumulative so each is independently meaningful. Methodology = bench.py
(scanned loop, chained carries, median-of-repeats, FLOPs floor).

Reference cost centers this decomposes: compute_factors / allreduce
(preconditioner.py:566-575), compute_inverses (:555-564),
precondition+clip (:577-585,661-682).

    python benchmarks/step_breakdown.py [--iters 30] [--polish 8 16]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import jax
import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench as B  # noqa: E402  (repo root: the timing methodology)
from distributed_kfac_pytorch_tpu import KFAC
from distributed_kfac_pytorch_tpu.models import cifar_resnet


def emit(obj):
    print(json.dumps(obj), flush=True)


def build(model, x, y, inv_freq, n_iters, mode, polish_iters=None,
          precond_dtype=None, kfac_kwargs=None):
    """One scanned runner for a cumulative phase ``mode``."""
    kw = dict(kfac_kwargs or {})
    if mode == 'factors_deferred':
        kw.setdefault('deferred_factor_reduction', True)
    if polish_iters is not None:
        kw['eigh_polish_iters'] = polish_iters
    if precond_dtype is not None:
        kw['precond_compute_dtype'] = precond_dtype
    kfac = KFAC(model, factor_update_freq=1, inv_update_freq=inv_freq,
                damping=0.003, lr=0.1, **kw)
    variables, kstate = kfac.init(jax.random.PRNGKey(0), x)
    params = variables['params']
    extra = {k: v for k, v in variables.items() if k != 'params'}
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    def loss(out):
        return B.loss_fn(out, y)

    def make_body(factor_update, inv_update, use_precond):
        def body(carry, _):
            params, opt_state, kstate, extra = carry
            loss_v, _, grads, captures, updated = (
                kfac.capture.loss_and_grads(
                    loss, params, x, extra_vars=extra,
                    mutable_cols=('batch_stats',)))
            if use_precond:
                g, kstate2 = kfac.step(kstate, grads, captures,
                                       factor_update=factor_update,
                                       inv_update=inv_update)
            else:
                g, kstate2 = grads, kstate
            updates, opt_state = tx.update(g, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, kstate2, {**extra, **updated}), loss_v
        return body

    if mode == 'sgd':
        def sgd_body(carry, _):
            params, opt_state, extra = carry

            def wrapped(p):
                out, updated = model.apply({'params': p, **extra}, x,
                                           mutable=['batch_stats'])
                return loss(out), updated
            (l, updated), grads = jax.value_and_grad(
                wrapped, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, {**extra, **updated}), l

        @jax.jit
        def run(carry):
            carry, losses = jax.lax.scan(sgd_body, carry, None,
                                         length=n_iters)
            return carry, losses[-1]
        return run, (params, opt_state, extra)

    if mode == 'capture':
        body = make_body(False, False, use_precond=False)
    elif mode == 'precond':
        body = make_body(False, False, use_precond=True)
    elif mode == 'factors':
        body = make_body(True, False, use_precond=True)
    elif mode == 'factors_deferred':
        # r14 deferred reduction at the same cadence shape as
        # 'factors': accumulate every iter, apply (factor_reduce) once
        # per inv_freq window — no firing, so the row isolates the
        # factor-statistics path like 'factors' does.
        def make_deferred_body(reduce_flag):
            def body(carry, _):
                params, opt_state, kstate, extra = carry
                loss_v, _, grads, captures, updated = (
                    kfac.capture.loss_and_grads(
                        loss, params, x, extra_vars=extra,
                        mutable_cols=('batch_stats',)))
                g, kstate2 = kfac.step(kstate, grads, captures,
                                       factor_update=True,
                                       inv_update=False,
                                       factor_reduce=reduce_flag)
                updates, opt_state = tx.update(g, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state, kstate2,
                        {**extra, **updated}), loss_v
            return body

        reduce_body = make_deferred_body(True)
        accum_body = make_deferred_body(False)

        def block(carry, _):
            carry, l0 = reduce_body(carry, None)
            carry, ls = jax.lax.scan(accum_body, carry, None,
                                     length=inv_freq - 1)
            return carry, ls[-1]

        @jax.jit
        def run(carry):
            carry, losses = jax.lax.scan(block, carry, None,
                                         length=n_iters // inv_freq)
            return carry, losses[-1]
        return run, (params, opt_state, kstate, extra)
    elif mode == 'full':
        inv_body = make_body(True, True, use_precond=True)
        plain_body = make_body(True, False, use_precond=True)

        def block(carry, _):
            carry, l0 = inv_body(carry, None)
            carry, ls = jax.lax.scan(plain_body, carry, None,
                                     length=inv_freq - 1)
            return carry, ls[-1]

        @jax.jit
        def run(carry):
            carry, losses = jax.lax.scan(block, carry, None,
                                         length=n_iters // inv_freq)
            return carry, losses[-1]
        return run, (params, opt_state, kstate, extra)
    else:
        raise ValueError(mode)

    @jax.jit
    def run(carry):
        carry, losses = jax.lax.scan(body, carry, None, length=n_iters)
        return carry, losses[-1]
    return run, (params, opt_state, kstate, extra)


def tuned_vs_default(args, model, x, y, inv_freq):
    """Replay a committed ``TUNED_*.json`` against the defaults.

    Both legs run the cumulative 'full' phase (factor EWMA every iter,
    amortized inverse firing) — the default at the reference cadence
    and the tuned leg with the artifact's knobs mapped onto raw KFAC
    kwargs (``autotune.kfac_overrides``); the composed ms/iter delta
    is the whole win/regression the artifact claims. Knobs the scanned
    harness cannot express (e.g. ``inv_pipeline_chunks`` — the scan
    fires monolithically) are surfaced in the row, not silently
    dropped.
    """
    from distributed_kfac_pytorch_tpu import autotune

    artifact = autotune.read_tuned(args.tuned_config)
    kw, tuned_inv_freq, ignored = autotune.kfac_overrides(
        artifact['best'])
    tuned_freq = tuned_inv_freq or inv_freq
    rows = {}
    for leg, kwargs, freq in (('default', None, inv_freq),
                              ('tuned', kw, tuned_freq)):
        n = (args.iters // freq) * freq or freq
        run, carry = build(model, x, y, freq, n, 'full',
                           kfac_kwargs=kwargs)
        rows[leg] = round(B.time_chained(run, carry, n,
                                         leg=f'tuned_ab_{leg}'), 2)
    emit({'phase': 'tuned_vs_default',
          'tuned_config': args.tuned_config,
          'workload': artifact.get('workload'),
          'artifact_platform': artifact.get('platform'),
          'backend': jax.default_backend(),
          'knobs': artifact['best'],
          'ignored_knobs': ignored,
          'default_inv_freq': inv_freq,
          'tuned_inv_freq': tuned_freq,
          'default_ms_per_iter': rows['default'],
          'tuned_ms_per_iter': rows['tuned'],
          'delta_ms_per_iter': round(rows['default'] - rows['tuned'],
                                     2)})


def lm_approx_rows(args):
    """Per-approximation factor-update cost rows (r13).

    For each ``--lm-d`` rung of the LM ladder: a scanned
    capture+precondition baseline (factor_update=False — everything
    the step pays EXCEPT the factor statistics, the r6 cumulative-
    phase methodology) and a capture+precondition+factor-EWMA leg per
    weight-sharing approximation ('expand' flattens B*T covariance
    rows, 'reduce' sums/averages over T first). The deltas isolate the
    A/G factor-statistic cost per approx — on the d2048 rung reduce's
    contraction sees seq x fewer rows, so its factor cost should drop
    toward ~T x, bounded by the rows-independent EWMA/symmetrize
    dim^2 passes that remain in both legs (the r13 claim the
    committed BENCH_r13_APPROX_COST.jsonl records; CPU provenance
    caveats per PERF.md).
    """
    import jax.numpy as jnp
    import optax as _optax

    from distributed_kfac_pytorch_tpu.models import transformer_lm

    for d in args.lm_d:
        model = transformer_lm.TransformerLM(
            vocab_size=args.lm_vocab, d_model=d, num_layers=1,
            num_heads=8, max_len=args.lm_seq, dropout=0.0,
            tie_weights=False)
        ids = jax.random.randint(jax.random.PRNGKey(1),
                                 (args.lm_batch, args.lm_seq), 0,
                                 args.lm_vocab)
        tgt = jax.random.randint(jax.random.PRNGKey(2),
                                 (args.lm_batch, args.lm_seq), 0,
                                 args.lm_vocab)

        def loss(out, tgt=tgt):
            return _optax.softmax_cross_entropy_with_integer_labels(
                out, tgt).mean()

        def make_run(approx, factor_update):
            kfac = KFAC(model, factor_update_freq=1,
                        inv_update_freq=args.iters * 10,
                        damping=0.003, lr=0.1,
                        kfac_approx=approx)
            variables, kstate = kfac.init(jax.random.PRNGKey(0), ids,
                                          train=False)
            params = variables['params']
            tx = _optax.sgd(0.1, momentum=0.9)
            opt_state = tx.init(params)

            def body(carry, _):
                params, opt_state, kstate = carry
                l, _, grads, captures, _ = (
                    kfac.capture.loss_and_grads(loss, params, ids,
                                                train=False))
                # The baseline leg still PRECONDITIONS (frozen
                # inverses): the factor-cost delta must not absorb
                # the approx-independent precondition matmuls.
                g, kstate = kfac.step(kstate, grads, captures,
                                      factor_update=factor_update,
                                      inv_update=False)
                updates, opt_state = tx.update(g, opt_state, params)
                params = _optax.apply_updates(params, updates)
                return (params, opt_state, kstate), l

            @jax.jit
            def run(carry):
                carry, losses = jax.lax.scan(body, carry, None,
                                             length=args.iters)
                return carry, losses[-1]
            return run, (params, opt_state, kstate)

        run, carry = make_run('expand', factor_update=False)
        base = B.time_chained(run, carry, args.iters,
                              leg=f'lm{d}_precond')
        row = {'phase': 'lm_approx_factor_cost', 'd_model': d,
               'seq': args.lm_seq, 'batch': args.lm_batch,
               'vocab': args.lm_vocab,
               'backend': jax.default_backend(),
               'precond_ms_per_iter': round(base, 2)}
        for approx in ('expand', 'reduce'):
            run, carry = make_run(approx, factor_update=True)
            ms = B.time_chained(run, carry, args.iters,
                                leg=f'lm{d}_factors_{approx}')
            row[f'factors_{approx}_ms_per_iter'] = round(ms, 2)
            row[f'factor_cost_{approx}'] = round(ms - base, 2)
        ce, cr = row['factor_cost_expand'], row['factor_cost_reduce']
        if cr > 0:
            row['expand_over_reduce'] = round(ce / cr, 2)

        # Statistics-only rows: time the A/G covariance COMPUTATION
        # alone (no EWMA write-back, no precondition) — the part of
        # the factor stage the approximation actually changes. The
        # whole-step deltas above bound the end-to-end win; these
        # isolate the ~T x contraction claim, which on a memory-bound
        # CPU is otherwise buried under the rows-independent dim^2
        # EWMA/assembly traffic both approxes pay equally (on TPU the
        # MXU contraction dominates the factor phase — PERF.md
        # roofline — so the whole-stage ratio tracks this number).
        kexp = KFAC(model, kfac_approx='expand')
        kred = KFAC(model, kfac_approx='reduce')
        variables, _ = kexp.init(jax.random.PRNGKey(0), ids,
                                 train=False)
        kred.init(jax.random.PRNGKey(0), ids, train=False)
        # kfaclint: waive[retrace-jit-in-loop] per-approx bench harness: one capture program per approx row
        _, _, _, captures, _ = jax.jit(
            lambda p: kexp.capture.loss_and_grads(
                loss, p, ids, train=False))(variables['params'])

        def stat_runner(specs):
            from distributed_kfac_pytorch_tpu import layers as L

            def body(carry, _):
                caps = carry
                probe = jnp.zeros((), jnp.float32)
                for name, spec in specs.items():
                    a = L.compute_a_factor(spec, caps[name]['a'])
                    g = L.compute_g_factor(spec, caps[name]['g'])
                    probe = probe + a.reshape(-1)[0] + g.reshape(-1)[0]
                # Perturb float captures so the chain cannot be CSE'd
                # across scan iterations (ids stay ints).
                caps = jax.tree.map(
                    lambda x: x * (1.0 + 1e-6)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x,
                    caps)
                return caps, probe

            @jax.jit
            def run(caps):
                caps, probes = jax.lax.scan(body, caps, None,
                                            length=args.iters)
                return caps, probes[-1]
            return run

        for approx, k in (('expand', kexp), ('reduce', kred)):
            run = stat_runner(k.specs)
            ms = B.time_chained(run, captures, args.iters,
                                leg=f'lm{d}_stats_{approx}')
            row[f'factor_stats_{approx}_ms_per_iter'] = round(ms, 2)
        se = row['factor_stats_expand_ms_per_iter']
        sr = row['factor_stats_reduce_ms_per_iter']
        if sr > 0:
            row['stats_expand_over_reduce'] = round(se / sr, 2)
        emit(row)


def lm_lowrank_rows(args):
    """Per-firing decomposition cost of the ENGAGED (transformer-FFN)
    factor bucket: exact eigh vs damped Cholesky vs r19 low-rank.

    For each ``--lm-d`` rung: build the rung's engaged factor stack —
    the ``(2, 4d, 4d)`` Wishart-class SPD bucket the config-4
    transformer's two FFN G-factors form — and time one firing of it
    under each backend:

      ``eigh``      the exact eigendecomposition (the reference eigen
                    path and the r19 parity oracle);
      ``cholesky``  the damped Cholesky inverse (today's 'auto'
                    large-dim dispatch);
      ``lowrank``   ``batched_lowrank_eigh`` in the WARM steady state
                    (the carried basis rides the chained carry, so
                    every timed call is the subspace-refresh +
                    projected-polish program a real firing runs).

    ``eigh_over_lowrank`` is the "per-firing decomposition cost
    reduced >= 3x vs exact eigh" acceptance number (PERF.md r19);
    ``cholesky_over_lowrank`` is the win over the current large-dim
    default. The whole-model firing (which dilutes both with the
    unchanged small-dim eigen work) rides in ``flagship_lm.py`` /
    ``firing_spread.py --lowrank``; quality in
    ``flagship_lm.py --lowrank-ab``.
    """
    import jax.numpy as jnp

    from distributed_kfac_pytorch_tpu.ops import (
        linalg,
        pallas_kernels,
    )

    for d in args.lm_d:
        dim = 4 * d
        rng = jax.random.PRNGKey(7)
        xs = jax.random.normal(rng, (2, 2 * dim, dim), jnp.float32)
        stack = (jnp.einsum('bni,bnj->bij', xs, xs) / (2 * dim)
                 + 1e-3 * jnp.eye(dim))
        row = {'phase': 'lm_lowrank_firing_cost', 'd_model': d,
               'engaged_dim': dim, 'stack': 2,
               'inv_lowrank_rank': args.lowrank_rank,
               'backend': jax.default_backend()}

        def timed(run, carry, leg):
            return round(B.time_chained(run, carry, 1, repeats=3,
                                        leg=f'lm{d}_lowrank_{leg}'),
                         2)

        def run_eigh(carry):
            s, t = carry
            qs, ds = jax.vmap(jnp.linalg.eigh)(s + t * 1e-6)
            return (s, t + 1), jnp.sum(ds).astype(jnp.float32)

        def run_chol(carry):
            s, t = carry
            inv = pallas_kernels.damped_inverse_stack(
                s + t * 1e-6, 0.003, 'cholesky')
            return (s, t + 1), jnp.sum(inv[:, 0, :]).astype(
                jnp.float32)

        def run_lowrank(carry):
            s, t, q = carry
            qs, ds = linalg.batched_lowrank_eigh(
                s + t * 1e-6, args.lowrank_rank, q_prev=q)
            return (s, t + 1, qs), jnp.sum(ds).astype(jnp.float32)

        # t*1e-6 perturbs the input each chained call so no backend
        # can cache a repeated decomposition out of the timed window.
        # kfaclint: waive[retrace-jit-in-loop] per-rung bench harness: one program per (rung, backend) row
        jit_eigh = jax.jit(run_eigh)
        # kfaclint: waive[retrace-jit-in-loop] per-rung bench harness: one program per (rung, backend) row
        jit_chol = jax.jit(run_chol)
        # kfaclint: waive[retrace-jit-in-loop] per-rung bench harness: one program per (rung, backend) row
        jit_lowrank = jax.jit(run_lowrank)
        row['firing_eigh_ms'] = timed(
            jit_eigh, (stack, jnp.float32(0)), 'eigh')
        row['firing_cholesky_ms'] = timed(
            jit_chol, (stack, jnp.float32(0)), 'cholesky')
        q0 = jnp.broadcast_to(jnp.eye(dim, args.lowrank_rank),
                              (2, dim, args.lowrank_rank))
        row['firing_lowrank_ms'] = timed(
            jit_lowrank, (stack, jnp.float32(0), q0), 'lowrank')
        if row['firing_lowrank_ms'] > 0:
            row['eigh_over_lowrank'] = round(
                row['firing_eigh_ms'] / row['firing_lowrank_ms'], 2)
            row['cholesky_over_lowrank'] = round(
                row['firing_cholesky_ms'] / row['firing_lowrank_ms'],
                2)
        emit(row)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--iters', type=int, default=30)
    p.add_argument('--polish', type=int, nargs='*', default=[16, 8])
    p.add_argument('--tuned-config', default=None, metavar='PATH',
                   help='replay a committed TUNED_*.json against the '
                        'defaults (tuned_vs_default row only; skips '
                        'the phase decomposition)')
    p.add_argument('--lm-approx', action='store_true',
                   help='r13 per-approx factor-update cost rows on the '
                        'LM ladder (expand vs reduce; skips the CIFAR '
                        'phase decomposition)')
    p.add_argument('--lm-lowrank', action='store_true',
                   help='r19 per-firing decomposition-cost rows on '
                        'the LM ladder (exact dispatch vs randomized '
                        'low-rank on the FFN dims; skips the CIFAR '
                        'phase decomposition)')
    p.add_argument('--lowrank-rank', type=int, default=64,
                   help='--lm-lowrank truncation rank')
    p.add_argument('--lm-d', type=int, nargs='+',
                   default=[512, 1024, 2048],
                   help='--lm-approx / --lm-lowrank d_model rungs')
    p.add_argument('--lm-seq', type=int, default=128)
    p.add_argument('--lm-batch', type=int, default=4)
    p.add_argument('--lm-vocab', type=int, default=512)
    args = p.parse_args(argv)

    if args.lm_approx:
        return lm_approx_rows(args)

    if args.lm_lowrank:
        return lm_lowrank_rows(args)

    on_tpu = jax.default_backend() == 'tpu'
    if on_tpu:
        model = cifar_resnet.get_model('resnet32')
        b = 512
    else:
        model = cifar_resnet.get_model('resnet20')
        b = 16
    x = jax.random.normal(jax.random.PRNGKey(1), (b, 32, 32, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (b,), 0, 10)
    inv_freq = 10
    n_iters = (args.iters // inv_freq) * inv_freq or inv_freq

    if args.tuned_config:
        return tuned_vs_default(args, model, x, y, inv_freq)

    kfac = KFAC(model, factor_update_freq=1, inv_update_freq=inv_freq)
    variables, _ = kfac.init(jax.random.PRNGKey(0), x)
    floor_ms = B.flops_floor_ms(kfac, variables, x, y,
                                mutable_cols=('batch_stats',))

    rows = {}
    for mode in ('sgd', 'capture', 'precond', 'factors',
                 'factors_deferred', 'full'):
        run, carry = build(model, x, y, inv_freq, n_iters, mode)
        ms = B.time_chained(run, carry, n_iters, floor_ms=floor_ms,
                            leg=mode)
        rows[mode] = round(ms, 2)
        print(json.dumps({'phase': mode, 'ms_per_iter': rows[mode]}),
              flush=True)
    # bf16 precondition A/B on the same cumulative 'precond' phase, so
    # the every-step precondition tax is attributed per dtype (the r6
    # knob; the delta against 'precond' is the whole saving/regression).
    import jax.numpy as jnp
    run, carry = build(model, x, y, inv_freq, n_iters, 'precond',
                       precond_dtype=jnp.bfloat16)
    ms = B.time_chained(run, carry, n_iters, floor_ms=floor_ms,
                        leg='precond_bf16')
    rows['precond_bf16'] = round(ms, 2)
    print(json.dumps({'phase': 'precond_bf16',
                      'ms_per_iter': rows['precond_bf16']}), flush=True)
    # r21 fused hot-path kernels A/B on the cumulative 'full' phase:
    # the delta against 'full' is the whole fused saving/regression
    # (on CPU the kernels run in interpret mode — parity provenance
    # only, rerun on TPU for decision-grade ms).
    run, carry = build(model, x, y, inv_freq, n_iters, 'full',
                       kfac_kwargs={'fused_factor_contraction': True,
                                    'fused_precondition': True})
    ms = B.time_chained(run, carry, n_iters, floor_ms=floor_ms,
                        leg='fused')
    rows['fused'] = round(ms, 2)
    print(json.dumps({'phase': 'fused',
                      'ms_per_iter': rows['fused']}), flush=True)
    for n in args.polish:
        run, carry = build(model, x, y, inv_freq, n_iters, 'full',
                           polish_iters=n)
        ms = B.time_chained(run, carry, n_iters, floor_ms=floor_ms,
                            leg=f'full_polish{n}')
        rows[f'full_polish{n}'] = round(ms, 2)
        print(json.dumps({'phase': f'full_polish{n}',
                          'ms_per_iter': rows[f'full_polish{n}']}),
              flush=True)
    deltas = {
        'capture_cost': round(rows['capture'] - rows['sgd'], 2),
        'precond_clip_cost': round(rows['precond'] - rows['capture'], 2),
        'precond_bf16_saving': round(rows['precond']
                                     - rows['precond_bf16'], 2),
        'factor_cost': round(rows['factors'] - rows['precond'], 2),
        # r14: single-chip program-cost delta of deferring the EWMA to
        # the window boundary (the collective saving needs a mesh).
        'deferred_reduce_delta': round(rows['factors_deferred']
                                       - rows['factors'], 2),
        'inverse_amortized_cost': round(rows['full'] - rows['factors'], 2),
        'fused_saving': round(rows['full'] - rows['fused'], 2),
    }
    print(json.dumps({'summary': rows, 'deltas': deltas}), flush=True)


if __name__ == '__main__':
    main()
