"""Per-shape conv A-factor implementation shootout (on-chip).

Times each patch-extraction implementation on each distinct conv shape
class of the tracked ResNet-32/CIFAR workload (plus the ImageNet stem
class), in isolation, so dispatch decisions rest on per-shape
measurements instead of whole-step inference — the discipline the
round-2 crosscov regression bought us.

Each timed leg scans ``inner`` A-factor computations over a chained
f32 carry (the input is nudged each iteration so no two contractions
see identical data), then applies bench.py's batch-window timing.
Every reading has a measured same-structure null-program baseline
(per-call dispatch + chain body) subtracted, so the reported ms are the
A-factor op alone and reproduce across ``--inner`` choices; the
baseline itself is reported per shape as ``overhead_baseline``.

    python benchmarks/conv_a_microbench.py [--inner 20]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench as B  # noqa: E402
from distributed_kfac_pytorch_tpu.ops import factors as F  # noqa: E402

# (label, batch, h, w, c, kernel, strides) — the distinct conv shape
# classes of the tracked workloads. CIFAR stages from cifar_resnet
# (batch 512); ImageNet classes cover every ResNet-50 3x3 stage plus
# the 7x7/stride-2 stem.
SHAPES = [
    ('cifar_stage1_c16_32x32', 512, 32, 32, 16, (3, 3), (1, 1)),
    ('cifar_stage2_c32_16x16', 512, 16, 16, 32, (3, 3), (1, 1)),
    ('cifar_stage3_c64_8x8', 512, 8, 8, 64, (3, 3), (1, 1)),
    ('imagenet_c64_56x56', 64, 56, 56, 64, (3, 3), (1, 1)),
    ('imagenet_c128_28x28', 64, 28, 28, 128, (3, 3), (1, 1)),
    ('imagenet_c256_14x14', 64, 14, 14, 256, (3, 3), (1, 1)),
    ('imagenet_c512_7x7', 64, 7, 7, 512, (3, 3), (1, 1)),
    ('imagenet_stem_c3_224x224_k7s2', 64, 224, 224, 3, (7, 7), (2, 2)),
    ('imagenet_c128_s2_56to28', 64, 56, 56, 128, (3, 3), (2, 2)),
]

IMPLS = ['slices', 'crosscov', 'dilated', 'pairs']


def build_runner(x0, impl, inner, kernel, strides, null=False):
    """``null=True`` builds the overhead-baseline program: identical
    scan/carry/chain structure with the A-factor computation replaced by
    a trivial stand-in — what it measures is the per-call dispatch
    (≈45 ms on the tunnel) plus the chain-body cost, which is
    subtracted from every impl reading so the reported numbers are the
    A-factor op alone and reproduce across --inner choices."""
    if impl is not None:
        os.environ['KFAC_CONV_PATCH_IMPL'] = impl
    d = kernel[0] * kernel[1] * x0.shape[-1] + 1

    def body(carry, _):
        x, acc = carry
        if null:
            a = jnp.full((d, d), jnp.float32(1e-9)) * x[0, 0, 0, 0]
        else:
            a = F.conv2d_a_factor(x, kernel, strides, 'SAME', True)
        # Chain: nudge the input by a value-dependent epsilon so the
        # next iteration's contraction is a genuinely new problem.
        x = x * (1.0 + 1e-6 * a[0, 0])
        return (x, acc + a), a[0, 0]

    @jax.jit
    def run(carry):
        carry, out = jax.lax.scan(body, carry, None, length=inner)
        return carry, out[-1]

    return run, (x0, jnp.zeros((d, d), jnp.float32))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--inner', type=int, default=20)
    args = p.parse_args(argv)

    for label, b, h, w, c, kernel, strides in SHAPES:
        x0 = jax.random.normal(jax.random.PRNGKey(0), (b, h, w, c),
                               jnp.float32)
        row = {'shape': label}
        run, carry = build_runner(x0, None, args.inner, kernel, strides,
                                  null=True)
        base = B.time_chained(run, carry, args.inner)
        row['overhead_baseline'] = round(base, 3)
        for impl in IMPLS:
            key = impl
            if impl == 'crosscov':
                # crosscov silently falls back to slices outside its
                # Wp*C <= 1024 regime — label such rows honestly so the
                # table never shows crosscov "competitive" on shapes
                # where it never ran.
                probe = F._conv_a_cov_crosscov(
                    x0[:1].astype(jnp.bfloat16), kernel, strides,
                    'SAME', None)
                if probe is None:
                    row['crosscov'] = 'fallback:slices'
                    continue
            run, carry = build_runner(x0, impl, args.inner, kernel,
                                      strides)
            try:
                ms = B.time_chained(run, carry, args.inner)
                row[key] = round(max(ms - base, 0.0), 3)
            except Exception as e:  # e.g. compile failure on one impl
                row[key] = f'error: {type(e).__name__}'
        os.environ.pop('KFAC_CONV_PATCH_IMPL', None)
        print(json.dumps(row), flush=True)


if __name__ == '__main__':
    main()
