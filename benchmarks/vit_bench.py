"""Measured ViT bench: Vision Transformer under encoder-attention K-FAC.

BEYOND the reference: it has no working attention workload (its LM
example ships broken — ``torch_language_model.py:253,277`` — and its
registry has no attention-bearing kinds: Linear/Conv2d/Embedding/
LSTMCell only, ``kfac/layers/__init__.py:13-36``). Here every ViT
weight layer is
preconditioned — the stride-P patch-embed conv plus the 6 encoder
Denses per block (``models/vit.py``) — and this bench records what
that costs on a real chip.

Cumulative phases (depthwise_bench methodology — scanned loop, chained
carries, median-of-repeats):

  sgd       plain SGD step (fwd+bwd+momentum)
  precond   + capture + preconditioning with frozen inverses + KL clip
  factors   + factor EWMA every iter
  full      + amortized inverse firing every ``inv_freq`` iters

MFU note: the reported ``mfu`` fields count registered-layer matmul
FLOPs only (``bench.model_flops_per_step``) — the attention
QK^T/AV einsums are excluded, so MFU is an underestimate (at S=197,
D=384 the attention terms are ~2*S/(12*D) ~ 9% of the projection
FLOPs).

    python benchmarks/vit_bench.py [--size small] [--batch 64]
        [--image 224] [--out VIT_r05.json]
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys

import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench as B  # noqa: E402  (repo root: the timing methodology)
from distributed_kfac_pytorch_tpu import KFAC
from distributed_kfac_pytorch_tpu.capture import extra_vars_of
from distributed_kfac_pytorch_tpu.models import vit
from distributed_kfac_pytorch_tpu.utils import enable_compilation_cache


def build(kfac, variables, kstate, model, x, y, inv_freq, n_iters, mode):
    params = variables['params']
    extra = extra_vars_of(variables)
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    def loss(out):
        return B.loss_fn(out, y)

    def make_body(factor_update, inv_update):
        def body(carry, _):
            params, opt_state, kstate, extra = carry
            loss_v, _, grads, captures, _ = (
                kfac.capture.loss_and_grads(
                    loss, params, x, extra_vars=extra,
                    intercept=factor_update))
            g, kstate2 = kfac.step(kstate, grads, captures,
                                   factor_update=factor_update,
                                   inv_update=inv_update)
            updates, opt_state = tx.update(g, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, kstate2, extra), loss_v
        return body

    if mode == 'sgd':
        def sgd_body(carry, _):
            params, opt_state, extra = carry

            def wrapped(p):
                return loss(model.apply({'params': p, **extra}, x))
            l, grads = jax.value_and_grad(wrapped)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, extra), l

        @jax.jit
        def run(carry):
            carry, losses = jax.lax.scan(sgd_body, carry, None,
                                         length=n_iters)
            return carry, losses[-1]
        return run, (params, opt_state, extra)

    if mode == 'precond':
        # Static-cadence non-factor step: capture-free (intercept=False),
        # preconditioning through the frozen inverses — the production
        # gated path (PERF.md round 4).
        body = make_body(False, False)
    elif mode == 'factors':
        body = make_body(True, False)
    elif mode == 'full':
        inv_body = make_body(True, True)
        plain_body = make_body(True, False)

        def block(carry, _):
            carry, _ = inv_body(carry, None)
            carry, ls = jax.lax.scan(plain_body, carry, None,
                                     length=inv_freq - 1)
            return carry, ls[-1]

        @jax.jit
        def run(carry):
            carry, losses = jax.lax.scan(block, carry, None,
                                         length=n_iters // inv_freq)
            return carry, losses[-1]
        return run, (params, opt_state, kstate, extra)
    else:
        raise ValueError(mode)

    # Donated carry on a fresh device copy (depthwise_bench rationale:
    # legs share one process, so donating the originals would delete
    # them for the next leg).
    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(carry):
        carry, losses = jax.lax.scan(body, carry, None, length=n_iters)
        return carry, losses[-1]
    carry0 = jax.tree.map(jnp.copy, (params, opt_state, kstate, extra))
    return run, carry0


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--iters', type=int, default=30)
    p.add_argument('--batch', type=int, default=64)
    p.add_argument('--image', type=int, default=224)
    p.add_argument('--size', default='small',
                   choices=['cifar', 'tiny', 'small', 'base'])
    p.add_argument('--model-dtype', default='bf16',
                   choices=['fp32', 'bf16'])
    p.add_argument('--bf16-factors', action='store_true')
    p.add_argument('--out', default='VIT_r05.json')
    args = p.parse_args(argv)
    enable_compilation_cache()

    on_tpu = jax.default_backend() == 'tpu'
    if not on_tpu:  # CPU shake-out config
        args.batch, args.image, args.size = 4, 32, 'cifar'
    dt = jnp.bfloat16 if args.model_dtype == 'bf16' else jnp.float32
    model = vit.get_model(1000, args.size, dtype=dt)
    if args.image % model.patch_size:
        raise SystemExit(f'--image {args.image} not divisible by '
                         f'patch {model.patch_size}')
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (args.batch, args.image, args.image, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (args.batch,), 0, 1000)
    inv_freq = 10
    n_iters = (args.iters // inv_freq) * inv_freq or inv_freq

    kfac = KFAC(model, factor_update_freq=1, inv_update_freq=inv_freq,
                damping=0.003, lr=0.1,
                factor_dtype=jnp.bfloat16 if args.bf16_factors else None)
    variables, kstate = kfac.init(jax.random.PRNGKey(0), x)
    floor_ms = B.flops_floor_ms(kfac, variables, x, y)
    flops = B.model_flops_per_step(
        kfac, variables['params'], x, y, extra_vars_of(variables),
        mutable_cols=())
    peak, _ = B.detected_tpu_peak() if on_tpu else (None, None)

    rows, mfu = {}, {}
    for mode in ('sgd', 'precond', 'factors', 'full'):
        run, carry = build(kfac, variables, kstate, model, x, y,
                           inv_freq, n_iters, mode)
        ms = B.time_chained(run, carry, n_iters, floor_ms=floor_ms,
                            leg=mode)
        rows[mode] = round(ms, 2)
        if peak:
            mfu[mode] = round(flops / (ms / 1e3) / peak, 4)
        print(json.dumps({'phase': mode, 'ms_per_iter': rows[mode]}),
              flush=True)

    # Composed production cadence (factors/50, inverses/500): base =
    # the gated capture-free step; the factor premium paid 1-in-50 and
    # the firing premium (read off the full leg's amortization) 1-in-500.
    factor_extra = rows['factors'] - rows['precond']
    firing_extra_per_iter = rows['full'] - rows['factors']  # at /10
    production = (rows['precond'] + factor_extra / 50
                  + firing_extra_per_iter * inv_freq / 500)
    out = {
        # Patch size from the model config, not a hardcoded 16: the
        # patch-4 'cifar' config used to mislabel as vit_cifar16_32px.
        'workload': f'vit_{args.size}{model.patch_size}_{args.image}px_'
                    f'b{args.batch}_{args.model_dtype}',
        'backend': jax.default_backend(),
        'n_registered_layers': len(kfac.specs),
        'unit': 'ms/iter',
        'phases': rows,
        'mfu_registered_layer_flops': mfu,
        'deltas': {
            'precond_gated_cost': round(rows['precond'] - rows['sgd'], 2),
            'factor_capture_cost': round(factor_extra, 2),
            'inverse_amortized_cost_at_10': round(firing_extra_per_iter,
                                                  2),
        },
        'vs_sgd': {
            'every_iter_factors': round(rows['factors'] / rows['sgd'], 3),
            'cifar_cadence_full': round(rows['full'] / rows['sgd'], 3),
            'production_f50_i500': round(production / rows['sgd'], 3),
        },
        'note': 'encoder-attention workload the reference has no '
                'working analogue of; mfu counts registered-layer '
                'matmuls only (attention einsums excluded — see '
                'module docstring)'
                + ('' if on_tpu else
                   '; NOT-TPU CAVEAT: measured on the CPU shake-out '
                   'config (batch 4, 32px, cifar size) — relative '
                   'phase structure only, no MFU, not comparable to '
                   'the v5e flagship rows'),
    }
    with open(args.out, 'w') as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out), flush=True)


if __name__ == '__main__':
    main()
