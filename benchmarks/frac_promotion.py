"""factor_batch_fraction promotion study: >=5 seeds x {1.0, 0.5, 0.25}.

Round-4 shipped the knob opt-in (default 1.0 = reference parity) with a
2-seed A/B that was inconclusive (seed noise ~4 points dominated the
arm gap). This driver runs the multi-seed study the round-4 verdict
asked for (#5): per (workload, fraction), >=5 seeds of the K-FAC arm at
that fraction's tuned damping (the round-4 finding: thinned factors
need a retuned damping — 0.03 at f=0.25 vs 0.003 full-batch — exactly
as lr is SGD's companion knob), reporting mean +/- std of
epochs-to-target and best val accuracy.

Each run is one `benchmarks/convergence.py --only kfac` invocation in a
subprocess (compile cache makes repeats cheap); the common target per
workload is fixed up front (the round-4 recorded both-tuned target for
the GN conv arm) so epochs-to-target is comparable across seeds and
fractions.

    python benchmarks/frac_promotion.py [--workload resnet20gn|mlp]
        [--seeds 0 1 2 3 4] [--out FRAC_PROMOTION.json]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Per-(workload, fraction) tuned hypers. Base points are the round-4
# both-tuned configs (CONVERGENCE_CONV_GN.json: conv lr 0.1 / damping
# 0.003 + alpha-0.5 decay; CONVERGENCE.json MLP study: lr 0.01 /
# damping 0.1, no damping schedule — the first cut of this study ran
# the MLP at the conv protocol and collapsed every seed, which is a
# protocol bug, not a fraction result). Thinned fractions take the
# round-4 A/B's 10x damping bump (thinner covariance sample -> more
# estimator noise -> more damping).
DAMPING = {
    'resnet20gn': {1.0: 0.003, 0.5: 0.003, 0.25: 0.03},
    'mlp': {1.0: 0.1, 0.5: 0.1, 0.25: 0.3},
}
BASE_LR = {'resnet20gn': 0.1, 'mlp': 0.01}
DAMPING_SCHED = {'resnet20gn': ['--damping-alpha', '0.5',
                                '--damping-decay', '10', '20'],
                 'mlp': []}

# Fixed common targets: the recorded both-tuned targets of the round-4
# studies (CONVERGENCE_CONV_GN.json / CONVERGENCE.json MLP study), so
# every run is scored against the same bar.
TARGETS = {'resnet20gn': 0.95, 'mlp': 0.9765}


def run_one(workload, seed, frac, args):
    out = f'/tmp/frac_{workload}_s{seed}_f{frac}.json'
    cmd = [sys.executable, 'benchmarks/convergence.py',
           '--model', workload, '--epochs', str(args.epochs),
           '--batch-size', '256', '--label-noise', '0.2',
           '--only', 'kfac', '--seed', str(seed),
           '--base-lr', str(BASE_LR[workload]),
           '--damping', str(DAMPING[workload][frac]),
           *DAMPING_SCHED[workload],
           '--factor-batch-fraction', str(frac),
           '--out', out]
    r = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=3600, cwd=REPO)
    if r.returncode != 0:
        tail = (r.stderr or '').strip().splitlines()[-1:]
        return {'error': f'rc={r.returncode}: {tail}'}
    with open(out) as f:
        d = json.load(f)
    curve = d['kfac']['curve']
    target = TARGETS[workload]
    ett = next((row['epoch'] + 1 for row in curve
                if row['val_acc'] >= target), None)
    return {'best_val': max(row['val_acc'] for row in curve),
            'epochs_to_target': ett,
            'final_val': curve[-1]['val_acc'],
            'wall_s': d['kfac']['wall_s']}


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--workload', default='resnet20gn',
                   choices=sorted(TARGETS))
    p.add_argument('--seeds', type=int, nargs='+',
                   default=[0, 1, 2, 3, 4])
    p.add_argument('--fractions', type=float, nargs='+',
                   default=[1.0, 0.5, 0.25],
                   choices=[1.0, 0.5, 0.25],
                   help='fractions with a tuned damping entry '
                        '(extend DAMPING for new values)')
    p.add_argument('--epochs', type=int, default=30)
    p.add_argument('--out', default='FRAC_PROMOTION.json')
    args = p.parse_args(argv)

    runs = {}
    for frac in args.fractions:
        for seed in args.seeds:
            key = f'f{frac}_s{seed}'
            print(f'=== {args.workload} {key} ===', flush=True)
            runs[key] = run_one(args.workload, seed, frac, args)
            print(json.dumps({key: runs[key]}), flush=True)

    summary = {}
    for frac in args.fractions:
        vals = [runs[f'f{frac}_s{s}'] for s in args.seeds
                if 'error' not in runs[f'f{frac}_s{s}']]
        if not vals:
            summary[str(frac)] = {'error': 'all seeds failed'}
            continue
        etts = [v['epochs_to_target'] for v in vals
                if v['epochs_to_target'] is not None]
        bests = [v['best_val'] for v in vals]
        summary[str(frac)] = {
            'n_seeds': len(vals),
            'n_reached_target': len(etts),
            'epochs_to_target_mean': (round(statistics.mean(etts), 2)
                                      if etts else None),
            'epochs_to_target_std': (round(statistics.stdev(etts), 2)
                                     if len(etts) > 1 else 0.0),
            'best_val_mean': round(statistics.mean(bests), 4),
            'best_val_std': (round(statistics.stdev(bests), 4)
                             if len(bests) > 1 else 0.0),
            'damping': DAMPING[args.workload][frac],
        }

    result = {'study': 'factor_batch_fraction_promotion',
              'workload': args.workload,
              'target_val_acc': TARGETS[args.workload],
              'protocol': 'K-FAC only, per-fraction tuned damping '
                          '(round-4 A/B), fixed lr 0.1 + damping-alpha '
                          '0.5 schedule, 20% label noise, fixed common '
                          'target; seed varies init/shuffle',
              'seeds': args.seeds, 'epochs': args.epochs,
              'summary': summary, 'runs': runs}
    with open(args.out, 'w') as f:
        json.dump(result, f, indent=1)
    print(json.dumps({'workload': args.workload, 'summary': summary}))


if __name__ == '__main__':
    main()
