"""Flagship LM on-chip numbers: Transformer-XL-scale decoder LM with
Linear-layer K-FAC (BASELINE tracked config 4, at the scale the round-4
verdict asked for: d_model >= 1024, FFN 4096, seq >= 1024).

The reference's LM example is broken as shipped
(torch_language_model.py:253 sets base_lr from the rank; :277 unpacks a
3-tuple into 4 — SURVEY.md §8), so there is no reference number to
match here: the bar is the framework's own SGD leg, with the same
<=1.3x production-cadence criterion the CNN flagship met.

Same phase-isolation design as flagship_resnet50.py (each leg is its
own subprocess: a dropped oversized compile poisons the tunneled device
session):

  sgd        plain autodiff + SGD momentum step
  nofactor   plain autodiff + precondition + KL clip (intercept=False —
             what (1-1/f) of production steps run)
  factors    capture + factor EWMA + precondition (the 1-in-f step)
  firing     inverse firing over the REAL factor set per method
             ('auto' first: it is the default; the xl factor set
             straddles the 640 eigen/cholesky cutoff — q/k/v/o sides
             1024/1025 go cholesky, nothing here is eigen except
             when --size small)

MFU is hand-counted with an LM-specific FLOP model (bench's
model_flops_per_step counts only K-FAC-registered matmuls — on a
transformer that misses attention scores/values and the tied-embedding
decoder matmul, which at vocab 32k is one of the largest matmuls in
the step):

  per layer fwd:   2*tok*4*d^2 (qkvo) + 4*B*T^2*d (QK^T + AV, full
                   T^2 — the causal mask zeroes but does not skip) +
                   2*tok*2*d*ffn (mlp in+out)
  head fwd:        2*tok*d*vocab (tied-embedding attend)
  fwd+bwd = 3x fwd (two same-size contractions per matmul backward).

    python benchmarks/flagship_lm.py [--size xl] [--seq 1024]
        [--batch 4] [--vocab 32768] [--model-dtype bf16]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def emit(obj):
    print(json.dumps(obj), flush=True)


def lm_flops_per_step(d_model, num_layers, mlp_ratio, batch, seq, vocab):
    tok = batch * seq
    per_layer = (2 * tok * 4 * d_model * d_model
                 + 4 * batch * seq * seq * d_model
                 + 2 * tok * 2 * d_model * (mlp_ratio * d_model))
    head = 2 * tok * d_model * vocab
    return 3 * (num_layers * per_layer + head)


# ---------------------------------------------------------------------------
# Single-phase worker (fresh process via --phase)
# ---------------------------------------------------------------------------

def _setup(args, with_kfac=True):
    import jax
    import jax.numpy as jnp
    import optax

    import bench as B  # noqa: F401  (enables the compile cache)
    from distributed_kfac_pytorch_tpu import KFAC
    from distributed_kfac_pytorch_tpu.models import transformer_lm

    dt = {None: None, 'fp32': jnp.float32, 'bf16': jnp.bfloat16}[
        args.model_dtype]
    model = transformer_lm.get_model(
        vocab_size=args.vocab, size=args.size, max_len=args.seq,
        dropout=0.0, dtype=dt,
        attn_block_size=args.attn_block_size)
    ids = jax.random.randint(jax.random.PRNGKey(1),
                             (args.batch, args.seq), 0, args.vocab)
    tgt = jax.random.randint(jax.random.PRNGKey(2),
                             (args.batch, args.seq), 0, args.vocab)
    if not with_kfac:
        # The SGD leg must not carry the multi-GB factor/inverse state
        # (at xl scale it alone RESOURCE_EXHAUSTs a 16 GB chip).
        variables = model.init(jax.random.PRNGKey(0), ids, train=False)
        return jax, jnp, optax, model, None, variables, None, ids, tgt
    kw = {}
    if args.inverse_method:
        kw['inverse_method'] = args.inverse_method
    if args.precond_dtype:
        # The r6 tentpole knob: bf16 precondition-contraction operands
        # (fp32 accumulation). With --bf16-inverses the stored inverses
        # are consumed resident — no fp32 upcast-on-read copy of the
        # 4096^2 operands that dominate the non-factor step.
        kw['precond_compute_dtype'] = {
            'fp32': jnp.float32, 'bf16': jnp.bfloat16}[args.precond_dtype]
    if args.bf16_factors:
        kw['factor_dtype'] = jnp.bfloat16
        kw['factor_compute_dtype'] = jnp.bfloat16
    if args.bf16_inverses:
        # Reference-legitimate storage policy (it computes inverses in
        # fp32 and stores in inv_dtype, which may be half precision —
        # kfac/layers/base.py:435,439 + preconditioner.py:149); at xl
        # scale fp32 inverse stacks alone are 3.2 GB and the scan
        # carry double-buffers.
        kw['inv_dtype'] = jnp.bfloat16
    if args.kfac_approx and args.kfac_approx != 'expand':
        # r13 weight-sharing approximation: 'reduce' switches every
        # sequence-shared Dense's factor statistics to the
        # sum-over-sequence form (and ties the embedding factor pair).
        kw['kfac_approx'] = args.kfac_approx
    kfac = KFAC(model, factor_update_freq=1, inv_update_freq=1,
                damping=0.003, lr=0.1, **kw)
    variables, kstate = kfac.init(jax.random.PRNGKey(0), ids, train=False)
    return jax, jnp, optax, model, kfac, variables, kstate, ids, tgt


def run_phase(args):
    import bench as B
    jax, jnp, optax, model, kfac, variables, kstate, ids, tgt = _setup(
        args, with_kfac=args.phase != 'sgd')
    params = variables['params']
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    def loss_fn(out):
        logits = out[0] if isinstance(out, tuple) else out
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgt).mean()

    mode = args.phase
    if mode == 'firing':
        # One real factor update so decomposed matrices are
        # covariance-shaped; factor shapes are batch/seq-independent,
        # so this shaping pass runs on TINY inputs (the full-size
        # forward + captures + full state RESOURCE_EXHAUSTs at xl).
        tiny = ids[:1, :128]
        tiny_tgt = tgt[:1, :128]

        def tiny_loss(out):
            logits = out[0] if isinstance(out, tuple) else out
            import optax as _o
            return _o.softmax_cross_entropy_with_integer_labels(
                logits, tiny_tgt).mean()

        _, _, _, captures, _ = jax.jit(
            lambda p: kfac.capture.loss_and_grads(
                tiny_loss, p, tiny, train=False))(params)
        factors = jax.jit(kfac.update_factors)(kstate, captures)
        del kstate, captures

        # The monolithic all-bucket firing program peaks at ~21 GB at
        # xl scale (fp32 stacks + Cholesky workspace + state double
        # buffer). The firing is embarrassingly separable by factor
        # dim, so each bucket is timed as its own chained program and
        # the per-firing cost is the sum — same methodology class as
        # the phase decomposition itself.
        import collections
        import functools

        by_dim = collections.defaultdict(list)
        for name, spec in kfac.specs.items():
            f = factors[name]
            for which in ('A', 'G'):
                m = f[which]
                if m.ndim != 2 or m.shape[0] != m.shape[-1]:
                    continue  # diagonal embedding A
                by_dim[m.shape[-1]].append(m)
        del factors
        # Free everything the bucket programs don't need: params,
        # momentum and the rest add ~3 GB that pushed the 4096/4097
        # bucket compiles over HBM.
        del params, opt_state, variables
        n = min(args.iters, 3)
        total_ms = 0.0
        parts = {}
        for dim in sorted(by_dim):
            stack = jnp.stack([m.astype(jnp.float32)
                               for m in by_dim[dim]])
            del by_dim[dim]
            method = kfac.method_for_dim(dim)
            if args.inverse_method == 'eigen':
                method = 'eigen'

            # Large-dim stacks (18 x 4096^2 fp32 = 1.2 GB) push the
            # batched Cholesky's workspace past HBM inside the scan —
            # lax.map over sub-chunks sequences the workspace (peak =
            # one chunk) without changing the work measured.
            k = stack.shape[0]
            chunks = 1
            if dim > 2048:
                chunks = next(c for c in range(1, k + 1)
                              if k % c == 0 and k // c <= 3)

            def chunked(fn, s):
                if chunks == 1:
                    return fn(s)
                cs = s.reshape(chunks, s.shape[0] // chunks,
                               *s.shape[1:])
                return jax.lax.map(fn, cs)

            if method == 'eigen':
                # The PRODUCTION eigen firing is the warm-start polish
                # (eigh_method 'auto' steady state), not a cold XLA
                # eigh — carry the basis through the chain like the
                # training path does.
                def body(carry, _):
                    from distributed_kfac_pytorch_tpu.ops import linalg
                    s, q = carry

                    def one(args_):
                        si, qi = args_
                        return linalg.batched_eigh(
                            si, 'auto', q_prev=qi,
                            polish_iters=kfac.eigh_polish_iters)

                    if chunks > 1:
                        cs = s.reshape(chunks, -1, *s.shape[1:])
                        cq = q.reshape(chunks, -1, *q.shape[1:])
                        qs, ds = jax.lax.map(one, (cs, cq))
                        qs = qs.reshape(q.shape)
                        ds = ds.reshape(s.shape[:2])
                    else:
                        qs, ds = one((s, q))
                    probe = qs.reshape(-1)[0] + ds.reshape(-1)[0]
                    return (s * (1.0 + 1e-5), qs), probe

                _, qs0 = jnp.linalg.eigh(stack)
                carry0 = (stack, qs0)
            else:
                def body(carry, _):
                    from distributed_kfac_pytorch_tpu.ops import (
                        pallas_kernels)
                    s = carry

                    def one(c):
                        return pallas_kernels.damped_inverse_stack(
                            c, 0.003, method)

                    inv = chunked(one, s)
                    probe = inv.reshape(-1)[0]
                    return s * (1.0 + 1e-5), probe

                carry0 = stack

            @functools.partial(jax.jit, donate_argnums=(0,))
            def run(c):
                c, probes = jax.lax.scan(body, c, None, length=n)
                return c, probes[-1]

            ms = B.time_chained(run, carry0, n, repeats=2,
                                max_attempts=2)
            parts[f'{dim}x{k}_{method}'] = round(ms, 2)
            total_ms += ms
            del stack
        out = {'phase_result': round(total_ms, 2),
               'bucket_parts': parts}
        if args.inv_pipeline_chunks > 1:
            # Firing-spread leg (r9): project the pipelined per-chunk
            # firing costs from the MEASURED per-bucket ms — the same
            # per-matrix granularity + LPT packer the runtime plan
            # uses (and the 'refinable from measured bucket_parts'
            # hook: these parts are exactly what inv_pipeline_costs
            # accepts). max_chunk_ms is the projected residual spike;
            # spike_reduction is the step-time-uniformity win the
            # on-chip rerun must confirm (PERF.md r9 decision rule).
            from distributed_kfac_pytorch_tpu.preconditioner import (
                plan_inverse_chunks)
            kc = args.inv_pipeline_chunks
            items = []
            for key, part_ms in parts.items():
                cnt = int(key.rsplit('_', 1)[0].split('x')[1])
                items += [((key, i), part_ms / cnt)
                          for i in range(cnt)]
            plan = plan_inverse_chunks(items, kc)
            loads = [0.0] * kc
            for key, cost in items:
                loads[plan[key]] += cost
            out['firing_spread'] = {
                'chunks': kc,
                'chunk_ms': [round(v, 2) for v in loads],
                'max_chunk_ms': round(max(loads), 2),
                'monolithic_ms': round(total_ms, 2),
                'spike_reduction': round(total_ms / max(loads), 2)}
        emit(out)
        return

    if mode == 'sgd':
        def body(carry, _):
            params, opt_state, kst = carry

            def wrapped(p):
                return loss_fn(model.apply({'params': p}, ids,
                                           train=False))
            l, grads = jax.value_and_grad(wrapped)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, kst), l
    else:
        flags = {'nofactor': (False, False),
                 'factors': (True, False)}[mode]

        def body(carry, _):
            params, opt_state, kst = carry
            l, _, grads, captures, _ = kfac.capture.loss_and_grads(
                loss_fn, params, ids, train=False,
                intercept=flags[0])
            g, kst = kfac.step(kst, grads, captures,
                               factor_update=flags[0],
                               inv_update=flags[1])
            updates, opt_state = tx.update(g, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, kst), l

    # Donated carry: time_chained feeds each call the previous call's
    # output, so the multi-GB state is single-buffered (without this
    # the xl nofactor leg's carry alone double-buffers past 16 GB).
    import functools

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(carry):
        carry, losses = jax.lax.scan(body, carry, None,
                                     length=args.iters)
        return carry, losses[-1]

    flops = lm_flops_per_step(model.d_model, model.num_layers, 4,
                              args.batch, args.seq, args.vocab)
    peak, _ = B.detected_tpu_peak()
    floor = flops / peak * 1e3 if peak else 0.0
    ms = B.time_chained(run, (params, opt_state, kstate), args.iters,
                        floor_ms=floor, leg=f'lm_{mode}')
    mfu = round(flops / (ms * 1e-3) / peak, 4) if peak else None
    emit({'phase_result': round(ms, 2), 'mfu': mfu})


# ---------------------------------------------------------------------------
# KFAC-expand vs KFAC-reduce vs SGD quality ladder (r13)
# ---------------------------------------------------------------------------

def run_quality_leg(args):
    """One (d_model, leg) rung of the --approx-ab scaling ladder.

    A short REAL training run (synthetic Markov corpus, the LM CLI's
    offline default) recording the per-step loss curve and steady-state
    ms/iter: legs 'sgd' (momentum baseline), 'expand' and 'reduce'
    (K-FAC under each weight-sharing approximation, identical
    hyperparameters otherwise — the curve difference isolates the
    approximation), plus the r14 staleness pair 'eager' (the default
    firing schedule) and 'stale' (``inv_staleness=1`` +
    ``deferred_factor_reduction=True`` — the composed overlap config a
    promotion would ship; the curve difference isolates the one-window
    inverse staleness, since deferred reduce is exact). Static cadence
    f=--ab-f / i=--ab-i through ``engine.cadence_flags`` like a
    production run; one jit variant per flag combination; step 0's
    compile wall is excluded from ms/iter. Quality curves, not
    microbenches — the PERF.md r13/r14 decision rules consume these
    next to step_breakdown's cost rows.
    """
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import bench as B  # noqa: F401  (compile cache)
    from distributed_kfac_pytorch_tpu import KFAC
    from distributed_kfac_pytorch_tpu.models import transformer_lm
    from distributed_kfac_pytorch_tpu.training import datasets, engine

    d = args.ab_d
    leg = args.quality_leg
    train_ids, _, vocab = datasets.get_lm_corpus(
        None, synthetic_size=max(args.ab_steps * args.ab_batch
                                 * args.ab_seq + args.ab_seq + 1,
                                 20_000),
        vocab_size=args.ab_vocab)
    model = transformer_lm.TransformerLM(
        vocab_size=vocab, d_model=d, num_layers=args.ab_layers,
        num_heads=8, max_len=args.ab_seq, dropout=0.0, tie_weights=True)

    def loss_of(logits, tgt):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgt).mean()

    tx = optax.sgd(args.ab_lr, momentum=0.9)
    f_freq, i_freq = args.ab_f, args.ab_i
    # Steps whose wall time is a jit trace+compile (each variant's
    # FIRST invocation), excluded from the spike stat below — every
    # flag combination compiles lazily mid-run, and a multi-second
    # compile wall would drown the eigh spike the metric exists to
    # show.
    compiled_at: set = set()
    cur_step = [0]
    if leg == 'sgd':
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, args.ab_seq), jnp.int32),
                               train=False)
        params = variables['params']
        opt_state = tx.init(params)

        @jax.jit
        def sgd_step(params, opt_state, x, y):
            def wrapped(p):
                return loss_of(model.apply({'params': p}, x,
                                           train=False), y)
            l, grads = jax.value_and_grad(wrapped)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, l

        def step(st, x, y, flags):
            if not compiled_at:
                compiled_at.add(cur_step[0])
            p, o, l = sgd_step(st[0], st[1], x, y)
            return (p, o), l
        state0 = (params, opt_state)
    else:
        # 'stale' = the composed r14 overlap config (staleness + the
        # exact deferred reduce); 'eager' = its matched default-
        # schedule baseline; 'expand'/'reduce' = the r13 approx legs;
        # 'lowrank' = the r19 randomized truncated path engaged on the
        # rung's FFN dims vs its matched 'exact' baseline.
        overlap = (dict(deferred_factor_reduction=True,
                        inv_staleness=1) if leg == 'stale' else {})
        if leg == 'lowrank':
            thr = args.ab_lowrank_threshold or 2 * d
            overlap = dict(inv_lowrank_rank=args.ab_lowrank_rank,
                           inv_lowrank_dim_threshold=thr)
        kfac = KFAC(model, factor_update_freq=f_freq,
                    inv_update_freq=i_freq, damping=0.003,
                    lr=args.ab_lr, kl_clip=0.001,
                    kfac_approx=(leg if leg in ('expand', 'reduce')
                                 else 'expand'),
                    **overlap)
        variables, kstate = kfac.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, args.ab_seq), jnp.int32), train=False)
        params = variables['params']
        opt_state = tx.init(params)
        variants = {}

        def step(st, x, y, flags):
            key = tuple(sorted(flags.items()))
            if key not in variants:
                compiled_at.add(cur_step[0])
                def impl(params, opt_state, kstate, x, y,
                         _flags=dict(flags)):
                    l, _, grads, captures, _ = (
                        kfac.capture.loss_and_grads(
                            lambda out: loss_of(out, y), params, x,
                            train=False,
                            intercept=_flags.get('factor_update',
                                                 True)))
                    g, kstate = kfac.step(kstate, grads, captures,
                                          **_flags)
                    updates, opt_state = tx.update(g, opt_state,
                                                   params)
                    params = optax.apply_updates(params, updates)
                    return params, opt_state, kstate, l
                variants[key] = jax.jit(impl)
            p, o, k, l = variants[key](st[0], st[1], st[2], x, y)
            return (p, o, k), l
        state0 = (params, opt_state, kstate)

    def leg_flags(i):
        return engine.cadence_flags(
            i, f_freq, i_freq,
            deferred_reduce=leg == 'stale',
            inv_staleness=1 if leg == 'stale' else 0)

    losses, times = [], []
    st = state0
    batches = datasets.bptt_batches(train_ids, args.ab_batch,
                                    args.ab_seq)
    for i, (x, y) in enumerate(batches):
        if i >= args.ab_steps:
            break
        flags = leg_flags(i)
        cur_step[0] = i
        t0 = _time.perf_counter()
        st, l = step(st, jnp.asarray(x), jnp.asarray(y), flags)
        jax.block_until_ready(l)
        times.append((_time.perf_counter() - t0) * 1000.0)
        losses.append(float(l))
    tail = losses[-max(len(losses) // 4, 1):]
    # Steady-state ms/iter over plain (non-fired, non-compile) steps.
    plain = [t for i, t in enumerate(times)
             if i not in compiled_at
             and engine.fired_stage(leg_flags(i)) is None]
    # Spike stat over every non-compile step: fired steps stay IN (the
    # spike is what staleness re-times), compile walls stay OUT.
    post = [t for i, t in enumerate(times) if i not in compiled_at]
    emit({'phase_result': round(float(np.mean(tail)), 4),
          'losses': [round(v, 4) for v in losses],
          'final_loss': round(float(np.mean(tail)), 4),
          'first_loss': round(losses[0], 4),
          'ms_per_iter_plain': (round(float(np.median(plain)), 2)
                                if plain else None),
          # Firing-spike uniformity (the number staleness moves):
          # max/median over post-warm steps.
          'spike_max_over_median': (
              round(float(np.max(post) / np.median(post)), 2)
              if post else None),
          'steps': len(losses)})


# ---------------------------------------------------------------------------
# Observability baseline (r10): reduce a short measured run to the
# committed gate baseline (BASELINE_OBS.json)
# ---------------------------------------------------------------------------

def run_obs_baseline(args):
    """Record a per-step metrics stream and write a gate baseline.

    Unlike the scan-based timing legs above, this loop dispatches the
    jitted step ONE host call at a time — the gate regresses the
    host-visible step-time distribution (p50/p95/p99), which only
    exists when the host sees every step. Cadence f=5/i=10 via the
    engine's own ``cadence_flags`` so fired-stage labels and the
    compile-per-variant shape match a real training run; memory
    records every 10 steps feed the peak-HBM metric (device allocator
    stats permitting — CPU runs record the state footprint only, and
    the committed baseline then simply carries no peak_hbm_bytes for
    the gate to compare). The recorded stream lands next to the
    baseline as ``<path>.source.jsonl`` — the evidence the committed
    number came from.
    """
    import time as _time

    jax, jnp, optax, model, kfac, variables, kstate, ids, tgt = _setup(
        args)
    from distributed_kfac_pytorch_tpu.observability import (
        gate as obs_gate,
        memory as obs_memory,
        sink as obs_sink,
    )
    from distributed_kfac_pytorch_tpu.training import engine

    params = variables['params']
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    def loss_fn(out):
        return optax.softmax_cross_entropy_with_integer_labels(
            out, tgt).mean()

    variants = {}

    def step(params, opt_state, kstate, f_flag, i_flag):
        key = (f_flag, i_flag)
        if key not in variants:
            def impl(params, opt_state, kstate, _f=f_flag, _i=i_flag):
                loss, _, grads, captures, _ = (
                    kfac.capture.loss_and_grads(
                        loss_fn, params, ids, train=False,
                        intercept=_f))
                g, kstate = kfac.step(kstate, grads, captures,
                                      factor_update=_f, inv_update=_i)
                updates, opt_state = tx.update(g, opt_state, params)
                params = optax.apply_updates(params, updates)
                return params, opt_state, kstate, loss
            variants[key] = jax.jit(impl)
        return variants[key](params, opt_state, kstate)

    f_freq, i_freq = 5, 10
    n_steps = max(int(args.iters), 4 * i_freq)
    spath = args.obs_baseline + '.source.jsonl'
    sink = obs_sink.JsonlMetricsSink(
        spath, meta={'bench': 'flagship_lm_obs_baseline',
                     'size': args.size, 'seq': args.seq,
                     'batch': args.batch, 'vocab': args.vocab,
                     'backend': jax.default_backend()})
    footprint = None
    # Warm every variant outside the recorded window (first calls are
    # compiles, not step times).
    for flags in ((True, True), (True, False), (False, False)):
        out = step(params, opt_state, kstate, *flags)
        jax.block_until_ready(out[0])
    for i in range(n_steps):
        flags = engine.cadence_flags(i, f_freq, i_freq)
        t0 = _time.perf_counter()
        params, opt_state, kstate, loss = step(
            params, opt_state, kstate, flags['factor_update'],
            flags['inv_update'])
        jax.block_until_ready(params)
        dt = (_time.perf_counter() - t0) * 1000.0
        sink.step_record(i, {'loss': loss}, host_step_ms=dt,
                         fired=engine.fired_stage(flags))
        if i % i_freq == 0:
            if footprint is None:
                footprint = obs_memory.state_footprint(kstate)
            sink.memory_record(
                i, device=obs_memory.device_memory_stats(),
                state=footprint)
    sink.close()
    records, _ = obs_sink.read_jsonl_tolerant(spath)
    metrics = obs_gate.gate_metrics(records)
    obj = obs_gate.write_baseline(
        metrics, args.obs_baseline,
        meta={'bench': 'flagship_lm_obs_baseline',
              'workload': (f'transformer_lm_{args.size}_seq{args.seq}'
                           f'_b{args.batch}_v{args.vocab}'),
              'backend': jax.default_backend(),
              'cadence': f'f{f_freq}_i{i_freq}',
              'source': spath})
    emit({'obs_baseline': args.obs_baseline, **obj['metrics']})


# ---------------------------------------------------------------------------
# Orchestrator
# ---------------------------------------------------------------------------

def spawn_phase(args, phase, inverse_method=None):
    cmd = [sys.executable, os.path.abspath(__file__), '--phase', phase,
           '--size', args.size, '--seq', str(args.seq),
           '--batch', str(args.batch), '--vocab', str(args.vocab),
           '--iters', str(args.iters)]
    if args.model_dtype:
        cmd += ['--model-dtype', args.model_dtype]
    if args.bf16_factors:
        cmd.append('--bf16-factors')
    if args.bf16_inverses:
        cmd.append('--bf16-inverses')
    if args.precond_dtype:
        cmd += ['--precond-dtype', args.precond_dtype]
    if inverse_method:
        cmd += ['--inverse-method', inverse_method]
    if args.kfac_approx:
        cmd += ['--kfac-approx', args.kfac_approx]
    if args.attn_block_size:
        cmd += ['--attn-block-size', str(args.attn_block_size)]
    if args.inv_pipeline_chunks > 1:
        cmd += ['--inv-pipeline-chunks', str(args.inv_pipeline_chunks)]
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=2400, cwd=REPO)
    except subprocess.TimeoutExpired:
        return 'failed: timeout', None, {}
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            obj = json.loads(line)
            extras = {k: v for k, v in obj.items()
                      if k not in ('phase_result', 'mfu')}
            return obj['phase_result'], obj.get('mfu'), extras
        except Exception:
            continue
    from bench import extract_failure_line
    msg = extract_failure_line(out.stderr, limit=160)
    return ('failed: ' + (msg or f'rc={out.returncode}'), None, {})


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--size', default='xl')
    p.add_argument('--seq', type=int, default=1024)
    p.add_argument('--batch', type=int, default=4)
    p.add_argument('--vocab', type=int, default=32768)
    p.add_argument('--iters', type=int, default=10)
    p.add_argument('--model-dtype', default='bf16',
                   choices=['fp32', 'bf16'])
    p.add_argument('--bf16-factors', action='store_true',
                   help='bf16 factor storage (halves the multi-GB '
                        'factor state at xl scale; decompositions stay '
                        'fp32 — the config-5 policy)')
    p.add_argument('--bf16-inverses', action='store_true',
                   help='bf16 inverse storage (inv_dtype; the '
                        'reference supports half-precision inverse '
                        'storage too — preconditioner.py:149)')
    p.add_argument('--inverse-method', default=None)
    p.add_argument('--precond-dtype', default=None,
                   choices=['fp32', 'bf16'],
                   help='precondition-contraction operand dtype (KFAC '
                        'precond_compute_dtype; default None = the '
                        'bit-identical legacy fp32-upcast path). bf16 '
                        'is the r6 A/B leg targeting the +18%% '
                        'every-step precondition tax; pair with '
                        '--bf16-inverses for the bf16-resident read.')
    p.add_argument('--attn-block-size', type=int, default=None,
                   help='memory-efficient chunked attention (long-seq '
                        'single-chip legs)')
    p.add_argument('--firing-methods', nargs='+',
                   default=['auto', 'cholesky', 'eigen'],
                   help='inverse methods to measure standalone firings '
                        'for (drop eigen at xl dims: the fp32-HIGHEST '
                        'polish at 4096+ is the recorded CNN-flagship '
                        'negative, seconds per firing)')
    p.add_argument('--precond-ab', action='store_true',
                   help='r6 precondition-dtype A/B: one sgd leg, then '
                        'the capture-free nofactor leg per dtype '
                        'variant (fp32 legacy / bf16 / bf16 with '
                        'bf16-resident inverses) — isolates the '
                        'every-step precondition tax per contraction '
                        'dtype without re-measuring the shared legs')
    p.add_argument('--inv-pipeline-chunks', type=int, default=1,
                   help='r9 firing-spread leg: with K > 1 the firing '
                        'phase additionally projects the pipelined '
                        'per-chunk firing costs from the measured '
                        'bucket_parts (LPT per-matrix packing, the '
                        'runtime plan) — max_chunk_ms is the residual '
                        'spike a pipelined window pays per step')
    p.add_argument('--kfac-approx', default=None,
                   choices=['expand', 'reduce'],
                   help='r13 weight-sharing approximation for the '
                        'K-FAC phases (factors/firing legs): reduce '
                        'sums/averages over the sequence axis before '
                        'the factor covariance')
    p.add_argument('--approx-ab', action='store_true',
                   help='r13 expand/reduce/SGD quality ladder: for '
                        'each --ladder d_model, run a short REAL '
                        'training leg per approximation (identical '
                        'hyperparameters) and emit the loss curves + '
                        'steady-state ms/iter — the committed evidence '
                        'rows (FLAGSHIP_LM_r13_APPROX.jsonl; PERF.md '
                        'r13 decision rule)')
    p.add_argument('--ladder', type=int, nargs='+',
                   default=[512, 1024, 2048],
                   help='--approx-ab d_model rungs (d512 -> d2048)')
    p.add_argument('--ab-steps', type=int, default=60,
                   help='training steps per --approx-ab leg')
    p.add_argument('--ab-seq', type=int, default=64)
    p.add_argument('--ab-batch', type=int, default=8)
    p.add_argument('--ab-vocab', type=int, default=512)
    p.add_argument('--ab-layers', type=int, default=2)
    p.add_argument('--ab-lr', type=float, default=0.1)
    p.add_argument('--ab-f', type=int, default=5,
                   help='--approx-ab factor-update cadence')
    p.add_argument('--ab-i', type=int, default=20,
                   help='--approx-ab inverse-update cadence')
    p.add_argument('--ab-d', type=int, default=512,
                   help='internal: quality-phase d_model')
    p.add_argument('--staleness-ab', action='store_true',
                   help='r14 inv_staleness convergence A/B: for each '
                        '--ladder d_model, run a short REAL training '
                        'leg with the default firing schedule '
                        '("eager") and one with inv_staleness=1 + '
                        'deferred_factor_reduction ("stale"), '
                        'identical hyperparameters — the loss-curve '
                        'difference isolates the one-window inverse '
                        'staleness (PERF.md r14 decision rule; '
                        'committed FLAGSHIP_LM_r14_STALENESS.jsonl)')
    p.add_argument('--lowrank-ab', action='store_true',
                   help='r19 randomized low-rank convergence A/B: for '
                        'each --ladder d_model, one leg with the '
                        'default exact dispatch ("exact") and one '
                        'with --ab-lowrank-rank engaged on the '
                        "rung's FFN factor dims (\"lowrank\", "
                        'threshold 2*d by default), identical '
                        'hyperparameters — the loss-curve difference '
                        'isolates the truncation (PERF.md r19 '
                        'decision rule; committed '
                        'FLAGSHIP_LM_r19_LOWRANK.jsonl)')
    p.add_argument('--ab-lowrank-rank', type=int, default=64,
                   help='--lowrank-ab truncation rank (must be below '
                        'every engaged dim)')
    p.add_argument('--ab-lowrank-threshold', type=int, default=0,
                   help='--lowrank-ab engagement threshold; 0 = '
                        "2*d_model (engages the rung's 4d FFN dims, "
                        'keeps the d-dim attention projections exact)')
    p.add_argument('--quality-leg', default=None,
                   choices=['sgd', 'expand', 'reduce', 'eager',
                            'stale', 'exact', 'lowrank'],
                   help='internal: which --approx-ab/--staleness-ab/'
                        '--lowrank-ab leg this subprocess runs')
    p.add_argument('--obs-baseline', default=None, metavar='PATH',
                   help='record a per-step metrics stream at this '
                        'config and reduce it to a committed '
                        'observability-gate baseline JSON (see '
                        'observability.gate; the stream itself lands '
                        'at PATH.source.jsonl). Use --size small on '
                        'CPU.')
    p.add_argument('--phase', default=None,
                   help='internal: run one phase in this process')
    args = p.parse_args(argv)

    if args.obs_baseline:
        return run_obs_baseline(args)

    if args.phase == 'quality':
        return run_quality_leg(args)

    if args.phase:
        return run_phase(args)

    if args.approx_ab or args.staleness_ab or args.lowrank_ab:
        import jax as _jax
        backend = _jax.default_backend()
        if args.approx_ab:
            legs, ab_label = ('sgd', 'expand', 'reduce'), 'kfac_approx'
        elif args.staleness_ab:
            legs, ab_label = ('eager', 'stale'), 'inv_staleness'
        else:
            legs, ab_label = ('exact', 'lowrank'), 'inv_lowrank'
        for d in args.ladder:
            for leg in legs:
                cmd = [sys.executable, os.path.abspath(__file__),
                       '--phase', 'quality', '--quality-leg', leg,
                       '--ab-d', str(d),
                       '--ab-steps', str(args.ab_steps),
                       '--ab-seq', str(args.ab_seq),
                       '--ab-batch', str(args.ab_batch),
                       '--ab-vocab', str(args.ab_vocab),
                       '--ab-layers', str(args.ab_layers),
                       '--ab-lr', str(args.ab_lr),
                       '--ab-f', str(args.ab_f),
                       '--ab-i', str(args.ab_i),
                       '--ab-lowrank-rank', str(args.ab_lowrank_rank),
                       '--ab-lowrank-threshold',
                       str(args.ab_lowrank_threshold)]
                row = {'config': 4, 'ab': ab_label,
                       'd_model': d, 'leg': leg, 'backend': backend,
                       'seq': args.ab_seq, 'batch': args.ab_batch,
                       'vocab': args.ab_vocab,
                       'layers': args.ab_layers,
                       'steps': args.ab_steps, 'lr': args.ab_lr,
                       'cadence': f'f{args.ab_f}_i{args.ab_i}'}
                if leg == 'lowrank':
                    row['inv_lowrank_rank'] = args.ab_lowrank_rank
                    row['inv_lowrank_dim_threshold'] = (
                        args.ab_lowrank_threshold or 2 * d)
                try:
                    out = subprocess.run(cmd, capture_output=True,
                                         text=True, timeout=7200,
                                         cwd=REPO)
                except subprocess.TimeoutExpired:
                    emit({**row, 'error': 'timeout'})
                    continue
                for line in reversed(out.stdout.strip().splitlines()):
                    try:
                        obj = json.loads(line)
                        obj.pop('phase_result', None)
                        emit({**row, **obj})
                        break
                    except Exception:
                        continue
                else:
                    from bench import extract_failure_line
                    emit({**row, 'error': extract_failure_line(
                        out.stderr, limit=160)
                        or f'rc={out.returncode}'})
        return

    if args.precond_ab:
        import jax as _jax
        backend = _jax.default_backend()
        workload = (f'transformer_lm_{args.size}_seq{args.seq}'
                    f'_b{args.batch}_v{args.vocab}')
        sgd_ms, sgd_mfu, _ = spawn_phase(args, 'sgd')
        emit({'config': 4, 'ab': 'precond_dtype', 'phase': 'sgd',
              'workload': workload, 'backend': backend,
              'model_dtype': args.model_dtype,
              'ms_per_iter': sgd_ms, 'mfu': sgd_mfu})
        for label, pdt, binv in (('fp32_legacy', None, False),
                                 ('bf16', 'bf16', False),
                                 ('bf16_resident', 'bf16', True)):
            args.precond_dtype = pdt
            args.bf16_inverses = binv
            ms, mfu, _ = spawn_phase(args, 'nofactor')
            row = {'config': 4, 'ab': 'precond_dtype', 'leg': label,
                   'phase': 'nofactor', 'workload': workload,
                   'backend': backend, 'model_dtype': args.model_dtype,
                   'precond_dtype': pdt, 'bf16_inverses': binv,
                   'ms_per_iter': ms, 'mfu': mfu, 'sgd': sgd_ms}
            if isinstance(ms, (int, float)) and isinstance(
                    sgd_ms, (int, float)):
                row['nonfactor_vs_sgd'] = round(ms / sgd_ms, 3)
            emit(row)
        return

    rows, mfus = {}, {}
    for mode in ('sgd', 'nofactor', 'factors'):
        rows[mode], mfus[mode], _ = spawn_phase(args, mode)
        emit({'config': 4, 'phase': mode, 'size': args.size,
              'seq': args.seq, 'batch': args.batch, 'vocab': args.vocab,
              'model_dtype': args.model_dtype,
              'precond_dtype': args.precond_dtype,
              'attn_block_size': args.attn_block_size,
              'ms_per_iter': rows[mode], 'mfu': mfus.get(mode)})
    firings = {}
    for method in args.firing_methods:
        firings[method], _, extras = spawn_phase(args, 'firing',
                                                 inverse_method=method)
        emit({'config': 4,
              'phase': f'inverse_firing_standalone_{method}',
              'ms_per_firing': firings[method], **extras})

    methods = [(m, v) for m, v in firings.items()
               if isinstance(v, (int, float))]
    ok = all(isinstance(rows.get(k), (int, float))
             for k in ('sgd', 'factors')) and methods
    if not ok:
        emit({'config': 4, 'partial': rows, 'firings': firings})
        return
    base = rows['nofactor'] if isinstance(
        rows.get('nofactor'), (int, float)) else rows['factors']
    factor_cost = max(rows['factors'] - base, 0.0)
    for fire_method, fire_ms in methods:
        out = {'config': 4, 'row_schema': 2,
               'workload': (f'transformer_lm_{args.size}_seq{args.seq}'
                            f'_b{args.batch}_v{args.vocab}'
                            + (f'_ab{args.attn_block_size}'
                               if args.attn_block_size else '')),
               'unit': 'ms/iter', 'sgd': rows['sgd'],
               'mfu_sgd': mfus.get('sgd'),
               'precond_dtype': args.precond_dtype,
               'every_iter': base,
               'factor_step_extra': round(factor_cost, 2),
               'inv_firing_method': fire_method,
               'inv_firing_ms': round(fire_ms, 2)}
        for label, f, i in (('stress_f1_i10', 1, 10),
                            ('imagenet_default_f10_i100', 10, 100),
                            ('production_f50_i500', 50, 500)):
            total = base + factor_cost / f + fire_ms / i
            out[label] = round(total, 2)
            out[label + '_vs_sgd'] = round(total / rows['sgd'], 3)
            if mfus.get('sgd'):
                out[label + '_mfu'] = round(
                    mfus['sgd'] * rows['sgd'] / total, 4)
        emit(out)


if __name__ == '__main__':
    main()
