"""Convergence evidence: K-FAC vs SGD epochs-to-accuracy on CIFAR.

The reference codebase's whole point is *faster convergence* (SC'20 /
KAISA: reduced time-to-75.9% on ImageNet; 5-epoch CIFAR smoke recipe,
scripts/longhorn_setup.md:20-29). This runner produces that evidence for
the TPU-native rebuild: identical model, data, LR schedule, weight
decay and momentum — the only difference is the K-FAC preconditioner —
and records per-epoch validation accuracy, epochs-to-target and final
accuracy.

Data: the deterministic synthetic class-conditional CIFAR set (this
environment has no data egress; pass --data-dir for real CIFAR pickles
— the code path is identical). Runs on whatever backend JAX resolves
(one TPU chip, or the CPU mesh for CI).

    python benchmarks/convergence.py --epochs 30 --out CONVERGENCE.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from distributed_kfac_pytorch_tpu.models import cifar_resnet
from distributed_kfac_pytorch_tpu.parallel import distributed as D
from distributed_kfac_pytorch_tpu.training import (
    datasets,
    engine,
    optimizers,
    utils,
)

from distributed_kfac_pytorch_tpu.utils import enable_compilation_cache


class _MLP:
    """BN-free MLP classifier over flattened images — the workload
    family K-FAC's advantage is cleanest on (no batch-stat lag under
    large preconditioned steps; the original K-FAC papers' domain)."""

    @staticmethod
    def build():
        import flax.linen as nn

        class MLP(nn.Module):
            @nn.compact
            def __call__(self, x, train=True):
                x = x.reshape(x.shape[0], -1)
                x = nn.Dense(512)(x)
                x = nn.relu(x)
                x = nn.Dense(256)(x)
                x = nn.relu(x)
                return nn.Dense(10)(x)
        return MLP()


def run_one(use_kfac: bool, args, data):
    (train_x, train_y), (val_x, val_y) = data
    model = (_MLP.build() if args.model == 'mlp'
             else cifar_resnet.get_model(
                 args.model, bn_momentum=args.bn_momentum))
    cfg = optimizers.OptimConfig(
        base_lr=args.base_lr, momentum=0.9, weight_decay=5e-4,
        warmup_epochs=args.warmup, lr_decay=args.lr_decay,
        workers=1,
        kfac_inv_update_freq=args.kfac_update_freq if use_kfac else 0,
        inv_pipeline_chunks=args.inv_pipeline_chunks,
        deferred_factor_reduction=args.deferred_factor_reduction,
        inv_staleness=args.inv_staleness,
        inv_lowrank_rank=args.inv_lowrank_rank,
        inv_lowrank_dim_threshold=args.inv_lowrank_dim_threshold,
        kfac_cov_update_freq=1, damping=args.damping,
        kl_clip=0.001, eigh_method=args.eigh_method,
        eigh_polish_iters=args.eigh_polish_iters,
        factor_batch_fraction=args.factor_batch_fraction,
        damping_alpha=args.damping_alpha,
        damping_schedule=args.damping_decay,
        kfac_update_freq_alpha=args.kfac_freq_alpha,
        kfac_update_freq_schedule=args.kfac_freq_decay)
    tx, lr_schedule, kfac, kfac_sched = optimizers.get_optimizer(
        model, cfg)

    x0 = jnp.zeros((2, 32, 32, 3), jnp.float32)
    if kfac is not None:
        variables, _ = kfac.init(jax.random.PRNGKey(args.seed), x0)
    else:
        variables = model.init(jax.random.PRNGKey(args.seed), x0)
    params = variables['params']
    extra = ({'batch_stats': variables['batch_stats']}
             if 'batch_stats' in variables else {})
    mutable = tuple(extra)
    mesh = D.make_kfac_mesh()
    opt_state = tx.init(params)

    def loss_fn(out, batch):
        return utils.label_smooth_loss(out, batch[1], 0.0)

    def metrics_fn(out, batch):
        return {'acc': utils.accuracy(out, batch[1])}

    if kfac is not None:
        dkfac = D.DistributedKFAC(kfac, mesh, params)
        kstate = dkfac.init_state(params)
        step_fn = dkfac.build_train_step(
            loss_fn, tx, metrics_fn=metrics_fn, mutable_cols=mutable)
    else:
        dkfac, kstate = None, None
        step_fn = engine.build_sgd_train_step(
            model, loss_fn, tx, mesh, metrics_fn=metrics_fn,
            mutable_cols=mutable)
    eval_step = engine.make_eval_step(
        model, loss_fn, mesh, model_args_fn=lambda b: (b[0], False))

    state = engine.TrainState(params=params, opt_state=opt_state,
                              kfac_state=kstate, extra_vars=extra)
    bn_steps = (engine.make_precise_bn_steps(model, mesh)
                if args.precise_bn > 0 and extra else None)
    curve = []
    t0 = time.perf_counter()
    for epoch in range(args.epochs):
        lr = lr_schedule(epoch)
        state.opt_state = optimizers.set_lr(state.opt_state, lr)
        hyper = {'lr': lr,
                 **(kfac_sched.params() if kfac_sched else {})}
        batches = datasets.epoch_batches(
            train_x, train_y, args.batch_size, seed=args.seed,
            epoch=epoch, augment=True)
        tm = engine.train_epoch(step_fn, state, batches, hyper)
        if bn_steps is not None:
            # Precise-BN: re-estimate running stats at the current
            # weights over a few forward-only training batches; used
            # for EVAL ONLY (training keeps its own EWMA state so the
            # optimization trajectory is untouched by the flag).
            import itertools
            recal = engine.precise_bn_recalibrate(
                model, state.params, state.extra_vars,
                itertools.islice(
                    datasets.epoch_batches(
                        train_x, train_y, args.batch_size,
                        seed=args.seed, epoch=10_000 + epoch,
                        augment=True),
                    args.precise_bn),
                mesh, steps=bn_steps)
            train_extra, state.extra_vars = state.extra_vars, recal
        vm = engine.evaluate(
            eval_step, state,
            datasets.epoch_batches(val_x, val_y, args.batch_size,
                                   shuffle=False, augment=False))
        if bn_steps is not None:
            state.extra_vars = train_extra
        if kfac_sched:
            kfac_sched.step(epoch + 1)
        curve.append({'epoch': epoch,
                      'train_loss': round(float(tm['loss']), 4),
                      'train_acc': round(float(tm['acc']), 4),
                      'val_loss': round(float(vm['loss']), 4),
                      'val_acc': round(float(vm['acc']), 4)})
        print(f'[{"kfac" if use_kfac else "sgd"}] {curve[-1]}',
              flush=True)
    wall = time.perf_counter() - t0
    return curve, wall


def epochs_to_target(curve, target):
    for row in curve:
        if row['val_acc'] >= target:
            return row['epoch'] + 1
    return None


def run_sweep(args, data):
    """Both-tuned comparison: LR-sweep each optimizer, pick each one's
    best configuration, compare epochs-to-target at a common target.

    This is the round-2 verdict's Missing #2 ask (and the papers'
    framing, BASELINE.md): K-FAC vs *LR-swept* SGD, both tuned, fixed
    seeds, on a non-separable task (--label-noise) — an honest
    quantitative epochs-to-accuracy table instead of a single-LR
    anecdote.
    """
    sweep: dict[str, dict] = {'kfac': {}, 'sgd': {}}
    damp_grid = args.kfac_damping_grid or [args.damping]
    bnm_grid = args.kfac_bn_momentum_grid or [args.bn_momentum]
    for use_kfac in (True, False):
        name = 'kfac' if use_kfac else 'sgd'
        for lr in args.lr_grid:
            for damping in (damp_grid if use_kfac else [args.damping]):
                for bnm in (bnm_grid if use_kfac
                            else [args.bn_momentum]):
                    a = argparse.Namespace(**vars(args))
                    a.base_lr = lr
                    a.damping = damping
                    a.bn_momentum = bnm
                    key = f'lr={lr}'
                    if use_kfac:
                        key += f',damping={damping}'
                        if len(bnm_grid) > 1:
                            key += f',bn_momentum={bnm}'
                    print(f'=== {name} {key} ===', flush=True)
                    curve, wall = run_one(use_kfac, a, data)
                    sweep[name][key] = {
                        'curve': curve, 'wall_s': round(wall, 1),
                        'best_val_acc': max(r['val_acc']
                                            for r in curve)}

    # Common target: the weaker optimizer's best achievable accuracy
    # (x0.995 tolerance) — both optimizers can reach it, so
    # epochs-to-target is defined for the comparison.
    best_per_opt = {n: max(e['best_val_acc'] for e in runs.values())
                    for n, runs in sweep.items()}
    target = min(best_per_opt.values()) * 0.995
    chosen = {}
    for name, runs in sweep.items():
        scored = []
        for key, entry in runs.items():
            ett = epochs_to_target(entry['curve'], target)
            entry['epochs_to_target'] = ett
            scored.append((ett if ett is not None else 10 ** 9,
                           -entry['best_val_acc'], key))
        scored.sort()
        best = scored[0][2]
        chosen[name] = {'config': best,
                        'epochs_to_target':
                            runs[best]['epochs_to_target'],
                        'best_val_acc': runs[best]['best_val_acc'],
                        'wall_s': runs[best]['wall_s']}

    result = {
        'study': 'both_tuned_lr_sweep',
        'workload': f'{args.model}_cifar_'
                    f'{"synthetic" if args.data_dir is None else "real"}',
        'backend': jax.default_backend(),
        'devices': jax.device_count(),
        'epochs': args.epochs, 'batch_size': args.batch_size,
        'label_noise': args.label_noise,
        'lr_grid': args.lr_grid,
        'kfac_damping_grid': damp_grid,
        'kfac_bn_momentum_grid': bnm_grid,
        'precise_bn': args.precise_bn,
        'sgd_damping_na': 'damping applies to K-FAC only',
        'target_val_acc': round(target, 4),
        'chosen': chosen,
        'sweep': {n: {key: {k: v for k, v in e.items() if k != 'curve'}
                      for key, e in runs.items()}
                  for n, runs in sweep.items()},
        'curves': {n: {key: e['curve'] for key, e in runs.items()}
                   for n, runs in sweep.items()},
    }
    with open(args.out, 'w') as f:
        json.dump(result, f, indent=1)
    summary = {k: result[k] for k in
               ('study', 'workload', 'label_noise', 'target_val_acc',
                'chosen')}
    print(json.dumps(summary))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--model', default='resnet32')
    p.add_argument('--epochs', type=int, default=30)
    p.add_argument('--batch-size', type=int, default=256)
    p.add_argument('--base-lr', type=float, default=0.1)
    p.add_argument('--warmup', type=float, default=2)
    p.add_argument('--lr-decay', type=int, nargs='+', default=[15, 23])
    p.add_argument('--kfac-update-freq', type=int, default=10)
    p.add_argument('--inv-pipeline-chunks', type=int, default=1,
                   help='pipelined inverse firing (r9): fire the '
                        'inverse work in K cost-balanced chunks across '
                        'each cadence window — the end-of-window drift '
                        'A/B arm for the step-time-uniformity knob '
                        '(chunked firings see fresher factors but '
                        'layer inverses are no longer simultaneous)')
    p.add_argument('--deferred-factor-reduction', action='store_true',
                   help='r14 deferred window-boundary factor '
                        'reduction (exact by EMA linearity; the A/B '
                        'arm only checks the composed schedule)')
    p.add_argument('--inv-staleness', type=int, default=0,
                   choices=[0, 1],
                   help='r14 one-window-stale off-critical-path '
                        'inverses — the staleness convergence A/B arm '
                        '(PERF.md r14 decision rule)')
    p.add_argument('--inv-lowrank-rank', type=int, default=0,
                   help='r19 randomized truncated-eigendecomposition '
                        'rank for dims >= --inv-lowrank-dim-threshold '
                        '(0 = exact dispatch) — the low-rank '
                        'convergence A/B arm (PERF.md r19)')
    p.add_argument('--inv-lowrank-dim-threshold', type=int,
                   default=2048)
    p.add_argument('--damping', type=float, default=0.003)
    # KFACParamScheduler knobs (the round-3 analysis prescribed a
    # damping/update-freq schedule for the conv/BN study; VERDICT r3 #6).
    p.add_argument('--damping-alpha', type=float, default=1.0)
    p.add_argument('--damping-decay', type=int, nargs='+', default=[])
    p.add_argument('--kfac-freq-alpha', type=float, default=1.0)
    p.add_argument('--kfac-freq-decay', type=int, nargs='+', default=[])
    p.add_argument('--precise-bn', type=int, default=0,
                   help='re-estimate BN running statistics over this '
                        'many forward-only train batches before each '
                        'eval (precise-BN; 0 = off). Eval-only: the '
                        'training EWMA state is untouched.')
    p.add_argument('--bn-momentum', type=float, default=0.9,
                   help='BatchNorm running-stat EWMA momentum (flax '
                        'convention; 0.9 = torch momentum 0.1, the '
                        'reference default)')
    p.add_argument('--kfac-bn-momentum-grid', type=float, nargs='+',
                   default=None,
                   help='sweep mode: BN momentum values for the K-FAC '
                        'leg (the stats-lag timescale is a K-FAC-'
                        'specific knob; default: just --bn-momentum)')
    p.add_argument('--eigh-method', default='auto')
    p.add_argument('--eigh-polish-iters', type=int, default=8)
    p.add_argument('--factor-batch-fraction', type=float, default=1.0,
                   help='thin the factor statistics to this fraction of '
                        'the batch (convergence A/B for the opt-in '
                        'factor_batch_fraction knob)')
    p.add_argument('--label-noise', type=float, default=0.0,
                   help='fraction of train labels flipped (fixed seed): '
                        'makes the synthetic task non-separable so the '
                        'accuracy target is meaningful')
    p.add_argument('--only', default=None, choices=['kfac', 'sgd'],
                   help='run a single optimizer (hyperparameter sweeps)')
    p.add_argument('--sweep', action='store_true',
                   help='LR-sweep BOTH optimizers over --lr-grid (both '
                        'tuned — the fair epochs-to-target comparison '
                        'the papers make) and record per-optimizer '
                        'bests plus the full sweep table')
    p.add_argument('--lr-grid', type=float, nargs='+',
                   default=[0.003, 0.01, 0.03, 0.1])
    p.add_argument('--kfac-damping-grid', type=float, nargs='+',
                   default=None,
                   help='sweep mode: damping values for the K-FAC leg '
                        '(its step-size-control knob, swept like SGD '
                        'sweeps lr; default: just --damping)')
    p.add_argument('--synthetic-size', type=int, default=4096)
    p.add_argument('--data-dir', default=None)
    p.add_argument('--seed', type=int, default=42)
    p.add_argument('--out', default='CONVERGENCE.json')
    p.add_argument('--platform', default=None, choices=['cpu', 'tpu'],
                   help='force a JAX platform (before first backend '
                        'use); cpu also simulates an 8-device mesh')
    args = p.parse_args(argv)

    if args.platform:
        jax.config.update('jax_platforms', args.platform)
        if args.platform == 'cpu':
            from distributed_kfac_pytorch_tpu.utils import (
                raise_cpu_collective_timeouts)
            raise_cpu_collective_timeouts()
            from distributed_kfac_pytorch_tpu import compat
            compat.set_cpu_device_count(8)
    # Persistent compile cache, AFTER platform resolution (the helper
    # itself refuses on a multi-device CPU configuration — the warm-read
    # segfault workaround, see utils.enable_compilation_cache).
    enable_compilation_cache()

    data = datasets.get_cifar(args.data_dir,
                              synthetic_size=args.synthetic_size)
    if args.label_noise > 0:
        (tx_, ty_), val = data
        rng = np.random.default_rng(123)
        flip = rng.random(len(ty_)) < args.label_noise
        noisy = rng.integers(0, int(ty_.max()) + 1,
                             len(ty_)).astype(ty_.dtype)
        ty_ = np.where(flip, noisy, ty_)
        data = ((tx_, ty_), val)
    print(f'backend={jax.default_backend()} devices={jax.device_count()} '
          f'train={data[0][0].shape} val={data[1][0].shape} '
          f'label_noise={args.label_noise}', flush=True)

    if args.sweep:
        return run_sweep(args, data)

    results_blocks = {}
    if args.only in (None, 'kfac'):
        kfac_curve, kfac_wall = run_one(True, args, data)
        results_blocks['kfac'] = (kfac_curve, kfac_wall)
    if args.only in (None, 'sgd'):
        sgd_curve, sgd_wall = run_one(False, args, data)
        results_blocks['sgd'] = (sgd_curve, sgd_wall)

    bests = {k: max(r['val_acc'] for r in c)
             for k, (c, _) in results_blocks.items()}
    # Epochs-to-target at the best accuracy EVERY ran optimizer reaches
    # (the papers' time-to-accuracy framing, BASELINE.md).
    target = min(bests.values()) * 0.995
    result = {
        'workload': f'{args.model}_cifar_'
                    f'{"synthetic" if args.data_dir is None else "real"}',
        'backend': jax.default_backend(),
        'devices': jax.device_count(),
        'epochs': args.epochs,
        'batch_size': args.batch_size,
        'label_noise': args.label_noise,
        'damping': args.damping,
        'inv_pipeline_chunks': args.inv_pipeline_chunks,
        'deferred_factor_reduction': args.deferred_factor_reduction,
        'inv_staleness': args.inv_staleness,
        'target_val_acc': round(target, 4),
    }
    if args.only:
        # Single-optimizer sweep artifact: emit ONLY the ran block so
        # the file can never masquerade as a two-optimizer comparison.
        result['only'] = args.only
    for k, (curve, wall) in results_blocks.items():
        result[k] = {'best_val_acc': bests[k],
                     'epochs_to_target': epochs_to_target(curve, target),
                     'wall_s': round(wall, 1),
                     'curve': curve}
    with open(args.out, 'w') as f:
        json.dump(result, f, indent=1)
    summary = {k: v for k, v in result.items()
               if k not in ('kfac', 'sgd')}
    for k in results_blocks:
        summary[f'{k}_best'] = bests[k]
        summary[f'{k}_epochs_to_target'] = result[k]['epochs_to_target']
    print(json.dumps(summary))


if __name__ == '__main__':
    main()
