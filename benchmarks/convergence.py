"""Convergence evidence: K-FAC vs SGD epochs-to-accuracy on CIFAR.

The reference codebase's whole point is *faster convergence* (SC'20 /
KAISA: reduced time-to-75.9% on ImageNet; 5-epoch CIFAR smoke recipe,
scripts/longhorn_setup.md:20-29). This runner produces that evidence for
the TPU-native rebuild: identical model, data, LR schedule, weight
decay and momentum — the only difference is the K-FAC preconditioner —
and records per-epoch validation accuracy, epochs-to-target and final
accuracy.

Data: the deterministic synthetic class-conditional CIFAR set (this
environment has no data egress; pass --data-dir for real CIFAR pickles
— the code path is identical). Runs on whatever backend JAX resolves
(one TPU chip, or the CPU mesh for CI).

    python benchmarks/convergence.py --epochs 30 --out CONVERGENCE.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))

from distributed_kfac_pytorch_tpu.models import cifar_resnet
from distributed_kfac_pytorch_tpu.parallel import distributed as D
from distributed_kfac_pytorch_tpu.training import (
    datasets,
    engine,
    optimizers,
    utils,
)


def run_one(use_kfac: bool, args, data):
    (train_x, train_y), (val_x, val_y) = data
    model = cifar_resnet.get_model(args.model)
    cfg = optimizers.OptimConfig(
        base_lr=args.base_lr, momentum=0.9, weight_decay=5e-4,
        warmup_epochs=args.warmup, lr_decay=args.lr_decay,
        workers=1,
        kfac_inv_update_freq=args.kfac_update_freq if use_kfac else 0,
        kfac_cov_update_freq=1, damping=0.003, kl_clip=0.001)
    tx, lr_schedule, kfac, kfac_sched = optimizers.get_optimizer(
        model, cfg)

    x0 = jnp.zeros((2, 32, 32, 3), jnp.float32)
    if kfac is not None:
        variables, _ = kfac.init(jax.random.PRNGKey(args.seed), x0)
    else:
        variables = model.init(jax.random.PRNGKey(args.seed), x0)
    params = variables['params']
    extra = {'batch_stats': variables['batch_stats']}
    mesh = D.make_kfac_mesh()
    opt_state = tx.init(params)

    def loss_fn(out, batch):
        return utils.label_smooth_loss(out, batch[1], 0.0)

    def metrics_fn(out, batch):
        return {'acc': utils.accuracy(out, batch[1])}

    if kfac is not None:
        dkfac = D.DistributedKFAC(kfac, mesh, params)
        kstate = dkfac.init_state(params)
        step_fn = dkfac.build_train_step(
            loss_fn, tx, metrics_fn=metrics_fn,
            mutable_cols=('batch_stats',))
    else:
        dkfac, kstate = None, None
        step_fn = engine.build_sgd_train_step(
            model, loss_fn, tx, mesh, metrics_fn=metrics_fn,
            mutable_cols=('batch_stats',))
    eval_step = engine.make_eval_step(
        model, loss_fn, mesh, model_args_fn=lambda b: (b[0], False))

    state = engine.TrainState(params=params, opt_state=opt_state,
                              kfac_state=kstate, extra_vars=extra)
    curve = []
    t0 = time.perf_counter()
    for epoch in range(args.epochs):
        lr = lr_schedule(epoch)
        state.opt_state = optimizers.set_lr(state.opt_state, lr)
        hyper = {'lr': lr,
                 **(kfac_sched.params() if kfac_sched else {})}
        batches = datasets.epoch_batches(
            train_x, train_y, args.batch_size, seed=args.seed,
            epoch=epoch, augment=True)
        tm = engine.train_epoch(step_fn, state, batches, hyper)
        vm = engine.evaluate(
            eval_step, state,
            datasets.epoch_batches(val_x, val_y, args.batch_size,
                                   shuffle=False, augment=False))
        if kfac_sched:
            kfac_sched.step(epoch + 1)
        curve.append({'epoch': epoch,
                      'train_loss': round(float(tm['loss']), 4),
                      'train_acc': round(float(tm['acc']), 4),
                      'val_loss': round(float(vm['loss']), 4),
                      'val_acc': round(float(vm['acc']), 4)})
        print(f'[{"kfac" if use_kfac else "sgd"}] {curve[-1]}',
              flush=True)
    wall = time.perf_counter() - t0
    return curve, wall


def epochs_to_target(curve, target):
    for row in curve:
        if row['val_acc'] >= target:
            return row['epoch'] + 1
    return None


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument('--model', default='resnet32')
    p.add_argument('--epochs', type=int, default=30)
    p.add_argument('--batch-size', type=int, default=256)
    p.add_argument('--base-lr', type=float, default=0.1)
    p.add_argument('--warmup', type=float, default=2)
    p.add_argument('--lr-decay', type=int, nargs='+', default=[15, 23])
    p.add_argument('--kfac-update-freq', type=int, default=10)
    p.add_argument('--synthetic-size', type=int, default=4096)
    p.add_argument('--data-dir', default=None)
    p.add_argument('--seed', type=int, default=42)
    p.add_argument('--out', default='CONVERGENCE.json')
    p.add_argument('--platform', default=None, choices=['cpu', 'tpu'],
                   help='force a JAX platform (before first backend '
                        'use); cpu also simulates an 8-device mesh')
    args = p.parse_args(argv)

    if args.platform:
        jax.config.update('jax_platforms', args.platform)
        if args.platform == 'cpu':
            jax.config.update('jax_num_cpu_devices', 8)

    data = datasets.get_cifar(args.data_dir,
                              synthetic_size=args.synthetic_size)
    print(f'backend={jax.default_backend()} devices={jax.device_count()} '
          f'train={data[0][0].shape} val={data[1][0].shape}', flush=True)

    kfac_curve, kfac_wall = run_one(True, args, data)
    sgd_curve, sgd_wall = run_one(False, args, data)

    best_sgd = max(r['val_acc'] for r in sgd_curve)
    best_kfac = max(r['val_acc'] for r in kfac_curve)
    # Epochs-to-target at the best accuracy BOTH reach (the papers'
    # time-to-accuracy framing, BASELINE.md).
    target = min(best_sgd, best_kfac) * 0.995
    result = {
        'workload': f'{args.model}_cifar_'
                    f'{"synthetic" if args.data_dir is None else "real"}',
        'backend': jax.default_backend(),
        'devices': jax.device_count(),
        'epochs': args.epochs,
        'batch_size': args.batch_size,
        'target_val_acc': round(target, 4),
        'kfac': {'best_val_acc': best_kfac,
                 'epochs_to_target': epochs_to_target(kfac_curve, target),
                 'wall_s': round(kfac_wall, 1),
                 'curve': kfac_curve},
        'sgd': {'best_val_acc': best_sgd,
                'epochs_to_target': epochs_to_target(sgd_curve, target),
                'wall_s': round(sgd_wall, 1),
                'curve': sgd_curve},
    }
    with open(args.out, 'w') as f:
        json.dump(result, f, indent=1)
    print(json.dumps({k: v for k, v in result.items()
                      if k not in ('kfac', 'sgd')}
                     | {'kfac_best': best_kfac, 'sgd_best': best_sgd,
                        'kfac_epochs_to_target':
                            result['kfac']['epochs_to_target'],
                        'sgd_epochs_to_target':
                            result['sgd']['epochs_to_target']}))


if __name__ == '__main__':
    main()
