from setuptools import find_packages, setup

setup(
    name='distributed-kfac-tpu',
    version='0.1.0',
    description=('TPU-native distributed K-FAC gradient preconditioner '
                 '(JAX/XLA/Pallas)'),
    packages=find_packages(exclude=('tests', 'examples', 'scripts')),
    python_requires='>=3.10',
    install_requires=['jax', 'flax', 'optax'],
)
